"""Attention ops: flash attention (Pallas TPU kernel + blockwise-scan core).

The 2017 reference has NO attention operator (SURVEY §5.7: long-sequence
support there is bucketing + cuDNN fused RNN only).  This module is the
new-capability half of the long-context story; the other half is ring
attention / context parallelism in ``mxnet_tpu.parallel.ring`` which reuses
the same blockwise online-softmax core over an ICI ring.

Design:

* ``_attn_reference`` — O(L^2)-memory softmax(QK^T)V, the numerics oracle.
* ``_flash_scan`` — blockwise online softmax as a ``lax.scan`` over K/V
  blocks: O(L) memory, pure JAX, runs on any backend, fully differentiable.
* ``_flash_pallas`` — the TPU kernel: grid (batch*heads, q_blocks, k_blocks),
  K innermost ("arbitrary" dimension semantics) with VMEM scratch carrying
  (m, l, acc) across K steps — the canonical TPU flash-attention schedule
  (MXU for the two dots, VPU for the online-softmax rescale).
* ``flash_attention`` — ``jax.custom_vjp``: forward picks the Pallas kernel
  on TPU (tile-aligned shapes) else the scan; backward recomputes blockwise
  from the saved (o, lse) residuals — the standard FA2 backward, written as
  plain JAX matmuls per K block so XLA schedules them on the MXU.

Shapes follow (batch, heads, seq, head_dim) throughout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import REQUIRED, pbool, pfloat, pint, register

NEG_INF = -1e30


def _attn_reference(q, k, v, causal=False, scale=None, kv_offset=0):
    """Quadratic-memory reference attention (numerics oracle for tests)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :] + kv_offset
        s = jnp.where(qi >= ki, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# blockwise scan core (shared by CPU path, backward pass, and ring attention)
# ---------------------------------------------------------------------------

def _flash_scan(q, k, v, causal, scale, block_k=512):
    """Blockwise attention as lax.scan over K blocks. Returns (out, lse).

    O(Lq·D + block_k·D) live memory per (batch, head); the scan is the
    XLA-native analog of the flash-attention loop.
    """
    orig_dtype = q.dtype
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    lk = k.shape[2]
    block_k = min(block_k, lk)
    nb = (lk + block_k - 1) // block_k
    pad = nb * block_k - lk
    if pad:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # lint: ok[recompile-hazard] block_k is a blocking-tuning knob with one default — per-value specialization is the intent
    kb = kf.reshape(kf.shape[0], kf.shape[1], nb, block_k, kf.shape[3])
    vb = vf.reshape(*kb.shape)
    kb = jnp.moveaxis(kb, 2, 0)  # (nb, B, H, block_k, D)
    vb = jnp.moveaxis(vb, 2, 0)

    b, h, lq, d = q.shape
    o0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)

    def step_masked(carry, kv):
        i, k_blk, v_blk = kv
        o, m, l = carry
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk) * scale
        kpos = i * block_k + jnp.arange(block_k)
        valid = kpos < lk
        if causal:
            qi = jnp.arange(lq)[:, None]
            valid = valid[None, :] & (qi >= kpos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (lq, block_k))
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        return (o_new, m_new, l_new), None

    (o, m, l), _ = jax.lax.scan(
        step_masked, (o0, m0, l0),
        (jnp.arange(nb), kb, vb))
    l = jnp.maximum(l, 1e-30)
    out = (o / l[..., None]).astype(orig_dtype)
    lse = m + jnp.log(l)
    return out, lse


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
               scale, causal, block_q, block_k, num_kb):
    """Online-softmax flash attention body; grid = (BH, num_qb, num_kb),
    K innermost with scratch (m, l, acc) carried across K steps."""
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            ki = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qi >= ki, s, NEG_INF)
        m_prev = m_scr[:, 0]                       # (block_q,)
        l_prev = l_scr[:, 0]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + p.sum(axis=-1)
        m_scr[:, 0] = m_cur
        l_scr[:, 0] = l_cur
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        # skip fully-masked K blocks (block above the diagonal)
        @pl.when(kb * block_k <= qb * block_q + (block_q - 1))
        def _():
            _body()
    else:
        _body()

    @pl.when(kb == num_kb - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, :, 0] = (m_scr[:, 0] + jnp.log(l))


def _flash_pallas(q, k, v, causal, scale, block_q=256, block_k=512,
                  interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    num_qb = lq // block_q
    num_kb = lk // block_k
    bh = b * h
    qr = q.reshape(bh, lq, d)
    kr = k.reshape(bh, lk, d)
    vr = v.reshape(bh, lk, d)

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_kb=num_kb)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, q_, k_: (b_, q_, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, q_, k_: (b_, k_, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, q_, k_: (b_, k_, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, q_, k_: (b_, q_, 0)),
            # lse rides as (bh, lq, 1) so the block's minor-two dims are
            # (block_q, 1) — sublane divisible by 8, lane equal to the
            # array dim.  A (1, block_q) block puts 1 in the sublane
            # slot and fails Mosaic's tile rule — which silently meant
            # this kernel NEVER lowered on real TPU until round 5 (the
            # d%128 gate routed the only hardware test through the scan
            # path)
            pl.BlockSpec((1, block_q, 1), lambda b_, q_, k_: (b_, q_, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # m
            pltpu.VMEM((block_q, 128), jnp.float32),   # l
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qr, kr, vr)
    # the reshape drops the trailing singleton the lse BlockSpec needed
    return out.reshape(b, h, lq, d), lse.reshape(b, h, lq)


def _use_pallas(q, k, block_q, block_k):
    if jax.default_backend() != "tpu":
        return False
    lq, lk = q.shape[2], k.shape[2]
    d = q.shape[3]
    return (lq % min(block_q, lq) == 0 and lk % min(block_k, lk) == 0
            and min(lq, block_q) % 8 == 0 and min(lk, block_k) % 128 == 0
            and d % 128 == 0)


# ---------------------------------------------------------------------------
# custom-vjp flash attention (public functional API)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    if _use_pallas(q, k, block_q, block_k):
        out, lse = _flash_pallas(q, k, v, causal, scale, block_q, block_k)
    else:
        out, lse = _flash_scan(q, k, v, causal, scale, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd_core(causal, scale, block_q, block_k, res, do, dlse=None):
    """FA2 backward: blockwise over K, plain-JAX matmuls (MXU via XLA).

    ``dlse`` (optional, (B,H,Lq) f32) is the cotangent of the logsumexp
    output: d lse_i / d s_ij = p_ij, so it enters as ``ds += p * dlse``
    — the one extra term that makes the (out, lse) PAIR differentiable
    (ring attention merges blocks through lse, so lse carries real
    gradients there)."""
    q, k, v, out, lse = res
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    of = out.astype(jnp.float32)
    delta = (dof * of).sum(axis=-1)                  # (B,H,Lq)

    lk = k.shape[2]
    bk = min(block_k, lk)
    nb = (lk + bk - 1) // bk
    pad = nb * bk - lk
    if pad:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = jnp.moveaxis(kf.reshape(kf.shape[0], kf.shape[1], nb, bk, kf.shape[3]), 2, 0)
    vb = jnp.moveaxis(vf.reshape(vf.shape[0], vf.shape[1], nb, bk, vf.shape[3]), 2, 0)

    lq = q.shape[2]

    def step(dq, kv):
        i, k_blk, v_blk = kv
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk) * scale
        kpos = i * bk + jnp.arange(bk)
        valid = kpos < lk
        if causal:
            qi = jnp.arange(lq)[:, None]
            valid = valid[None, :] & (qi >= kpos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (lq, bk))
        p = jnp.where(valid, jnp.exp(s - lse[..., None]), 0.0)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v_blk)
        dsum = dp - delta[..., None]
        if dlse is not None:
            dsum = dsum + dlse.astype(jnp.float32)[..., None]
        ds = p * dsum * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k_blk)
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_b, dv_b) = jax.lax.scan(step, dq0, (jnp.arange(nb), kb, vb))
    dk = jnp.moveaxis(dk_b, 0, 2).reshape(kf.shape)[:, :, :lk]
    dv = jnp.moveaxis(dv_b, 0, 2).reshape(vf.shape)[:, :, :lk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd(causal, scale, block_q, block_k, res, do):
    return _flash_bwd_core(causal, scale, block_q, block_k, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# (out, lse) pair — the differentiable unit ring attention merges
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_pair(q, k, v, causal, scale, block_q, block_k):
    out, res = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out, res[4]


def _flash_pair_fwd(q, k, v, causal, scale, block_q, block_k):
    out, res = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return (out, res[4]), res


def _flash_pair_bwd(causal, scale, block_q, block_k, res, cts):
    do, dlse = cts
    return _flash_bwd_core(causal, scale, block_q, block_k, res, do,
                           dlse=dlse)


_flash_pair.defvjp(_flash_pair_fwd, _flash_pair_bwd)


def flash_attention_with_lse(q, k, v, causal=False, softmax_scale=None,
                             block_q=256, block_k=512):
    """Like :func:`flash_attention` but also returns the per-query
    logsumexp (B, H, Lq) — differentiable in BOTH outputs, which is what
    lets ``parallel.ring`` merge per-shard kernel calls with gradients
    flowing through the merge weights."""
    if softmax_scale is None:
        softmax_scale = float(1.0 / np.sqrt(q.shape[-1]))
    return _flash_pair(q, k, v, bool(causal), float(softmax_scale),
                       int(block_q), int(block_k))


def flash_attention(q, k, v, causal=False, softmax_scale=None,
                    block_q=256, block_k=512):
    """Memory-efficient attention. q/k/v: (batch, heads, seq, head_dim)."""
    if softmax_scale is None:
        softmax_scale = float(1.0 / np.sqrt(q.shape[-1]))
    return _flash(q, k, v, bool(causal), float(softmax_scale),
                  int(block_q), int(block_k))


# ---------------------------------------------------------------------------
# registered ops
# ---------------------------------------------------------------------------

def _flash_attention_op(attrs, inputs, aux, is_train, rng):
    q, k, v = inputs
    return [flash_attention(q, k, v, causal=attrs["causal"],
                            softmax_scale=attrs["softmax_scale"] or None,
                            block_q=attrs["block_q"], block_k=attrs["block_k"])]


register("_contrib_FlashAttention", _flash_attention_op,
         arguments=("query", "key", "value"),
         params={"causal": (pbool, False),
                 "softmax_scale": (pfloat, 0.0),
                 "block_q": (pint, 256), "block_k": (pint, 512)},
         aliases=("FlashAttention",), hint="flashattention")


def _mha_op(attrs, inputs, aux, is_train, rng):
    """MultiHeadAttention: (B, L, E) inputs, fused qkv projection weights."""
    x_q, x_kv, w_qkv, w_out = inputs[:4]
    b_qkv = inputs[4] if len(inputs) > 4 else None
    b_out = inputs[5] if len(inputs) > 5 else None
    num_heads = attrs["num_heads"]
    e = x_q.shape[-1]
    hd = e // num_heads
    wq, wk, wv = jnp.split(w_qkv, 3, axis=0)  # each (E, E)
    q = jnp.einsum("ble,fe->blf", x_q, wq)
    kk = jnp.einsum("ble,fe->blf", x_kv, wk)
    vv = jnp.einsum("ble,fe->blf", x_kv, wv)
    if b_qkv is not None:
        bq, bk_, bv = jnp.split(b_qkv, 3)
        q, kk, vv = q + bq, kk + bk_, vv + bv

    def heads(t):
        return t.reshape(t.shape[0], t.shape[1], num_heads, hd).transpose(0, 2, 1, 3)

    o = flash_attention(heads(q), heads(kk), heads(vv), causal=attrs["causal"])
    o = o.transpose(0, 2, 1, 3).reshape(x_q.shape[0], x_q.shape[1], e)
    out = jnp.einsum("ble,fe->blf", o, w_out)
    if b_out is not None:
        out = out + b_out
    return [out]


register("_contrib_MultiHeadAttention", _mha_op,
         arguments=lambda a: (["query", "key_value", "qkv_weight", "out_weight"]
                              + ([] if a["no_bias"] else ["qkv_bias", "out_bias"])),
         params={"num_heads": (pint, REQUIRED), "causal": (pbool, False),
                 "no_bias": (pbool, False)},
         aliases=("MultiHeadAttention",), hint="multiheadattention")
