"""Fused LSTM sequence kernels (Pallas) — the cuDNN fused-RNN analog.

Reference: ``src/operator/cudnn_rnn-inl.h:127`` (cudnnRNNForwardTraining)
exists because per-timestep kernel launches starved the GPU; the XLA
analog of that overhead is the pile of small per-step HLOs inside the
``lax.scan`` cell (gate splits/sigmoids/muls — each a distinct op with
fixed cost at [N,H]-sized operands).  These kernels run the WHOLE
recurrence in one Pallas call, everything VMEM-resident: per step, four
MXU dots plus fused VPU gate math, no inter-HLO overhead.

Layout rules (Mosaic): the LANE (last) axis is never sliced at non-128
multiples, and kernels do no in-kernel reshape/transpose.  Gates
therefore ride a dedicated leading axis — projections are
``(T, 4, N, H)``, recurrent weights ``(4, H, H)`` with ``w4[k]`` the
(in, out) matrix of gate k, biases ``(4, H)`` — and every gate access
is a static index.

Backward is a second kernel over the saved activations (post-activation
gates + cell states), wired through ``jax.custom_vjp`` so ``jax.grad``
of a graph containing the fused op works like any other.  CPU runs use
``interpret=True`` (same code, executed by the Pallas interpreter);
hardware parity is pinned in ``tests_tpu/``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# VMEM budget guard: xp + saved gates dominate (two (T,4,N,H) f32 bufs)
_VMEM_LIMIT_BYTES = 10 * 1024 * 1024


def fits(seq_len, batch, hidden, dtype) -> bool:
    if dtype != jnp.float32:
        return False
    per = seq_len * 4 * batch * hidden * 4
    return 2 * per + 3 * seq_len * batch * hidden * 4 < _VMEM_LIMIT_BYTES


def _nt(a, b):
    """a (N, K) x b (M, K) -> (N, M): contract last with last."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _tn(a, b):
    """a (K, N) x b (K, M) -> (N, M): contract first with first."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _fwd_kernel(T, xp_ref, w4_ref, bh_ref, h0_ref, c0_ref,
                ys_ref, gates_ref, cs_ref, hT_ref, cT_ref):
    w4 = w4_ref[...]            # (4, H, H): per-gate (in, out)
    bh = bh_ref[...]            # (4, H)

    def body(t, carry):
        h, c = carry
        xp = xp_ref[pl.ds(t, 1)][0]   # (4, N, H)
        z = [jnp.dot(h, w4[k], preferred_element_type=jnp.float32)
             for k in range(4)]
        i = jax.nn.sigmoid(xp[0] + z[0] + bh[0])
        f = jax.nn.sigmoid(xp[1] + z[1] + bh[1])
        g = jnp.tanh(xp[2] + z[2] + bh[2])
        o = jax.nn.sigmoid(xp[3] + z[3] + bh[3])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        ys_ref[pl.ds(t, 1)] = h[None]
        cs_ref[pl.ds(t, 1)] = c[None]
        gates_ref[pl.ds(t, 1)] = jnp.stack([i, f, g, o])[None]
        return h, c

    h, c = jax.lax.fori_loop(0, T, body, (h0_ref[...], c0_ref[...]))
    hT_ref[...] = h
    cT_ref[...] = c


def _bwd_kernel(T, gates_ref, cs_ref, ys_ref, w4_ref, h0_ref, c0_ref,
                dys_ref, dhT_ref, dcT_ref,
                dxp_ref, dw4_ref, dbh_ref, dh0_ref, dc0_ref):
    w4 = w4_ref[...]
    dw4_ref[...] = jnp.zeros(dw4_ref.shape, dw4_ref.dtype)
    dbh_ref[...] = jnp.zeros(dbh_ref.shape, dbh_ref.dtype)

    def body(kk, carry):
        dh, dc = carry
        t = T - 1 - kk
        tp = jnp.maximum(t - 1, 0)
        gs = gates_ref[pl.ds(t, 1)][0]   # (4, N, H)
        i, f, g, o = gs[0], gs[1], gs[2], gs[3]
        c = cs_ref[pl.ds(t, 1)][0]
        c_prev = jnp.where(t > 0,
                           cs_ref[pl.ds(tp, 1)][0],
                           c0_ref[...])
        h_prev = jnp.where(t > 0,
                           ys_ref[pl.ds(tp, 1)][0],
                           h0_ref[...])
        dh = dh + dys_ref[pl.ds(t, 1)][0]
        tc = jnp.tanh(c)
        do = dh * tc
        dc = dc + dh * o * (1.0 - tc * tc)
        dz = [
            dc * g * i * (1.0 - i),           # d pre-act input gate
            dc * c_prev * f * (1.0 - f),      # d pre-act forget gate
            dc * i * (1.0 - g * g),           # d pre-act candidate
            do * o * (1.0 - o),               # d pre-act output gate
        ]
        dxp_ref[pl.ds(t, 1)] = jnp.stack(dz)[None]
        dh_new = jnp.zeros_like(dh)
        for k in range(4):
            dbh_ref[k, :] += jnp.sum(dz[k], axis=0)
            dw4_ref[k] += _tn(h_prev, dz[k])   # (H_in, H_out)
            dh_new = dh_new + _nt(dz[k], w4[k])
        dc = dc * f
        return dh_new, dc

    dh, dc = jax.lax.fori_loop(0, T, body, (dhT_ref[...], dcT_ref[...]))
    dh0_ref[...] = dh
    dc0_ref[...] = dc


def _infer_kernel(T, xp_ref, w4_ref, bh_ref, h0_ref, c0_ref,
                  ys_ref, hT_ref, cT_ref):
    """Forward without residuals: inference writes only ys/hT/cT —
    the (T,4,N,H) gates + (T,N,H) cs buffers are training-only."""
    w4 = w4_ref[...]
    bh = bh_ref[...]

    def body(t, carry):
        h, c = carry
        xp = xp_ref[pl.ds(t, 1)][0]
        z = [jnp.dot(h, w4[k], preferred_element_type=jnp.float32)
             for k in range(4)]
        i = jax.nn.sigmoid(xp[0] + z[0] + bh[0])
        f = jax.nn.sigmoid(xp[1] + z[1] + bh[1])
        g = jnp.tanh(xp[2] + z[2] + bh[2])
        o = jax.nn.sigmoid(xp[3] + z[3] + bh[3])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        ys_ref[pl.ds(t, 1)] = h[None]
        return h, c

    h, c = jax.lax.fori_loop(0, T, body, (h0_ref[...], c0_ref[...]))
    hT_ref[...] = h
    cT_ref[...] = c


def _run_infer(xp, w4, bh, h0, c0, interpret):
    T, _, N, H = xp.shape
    out_shapes = [
        jax.ShapeDtypeStruct((T, N, H), jnp.float32),
        jax.ShapeDtypeStruct((N, H), jnp.float32),
        jax.ShapeDtypeStruct((N, H), jnp.float32),
    ]
    return pl.pallas_call(
        functools.partial(_infer_kernel, T), out_shape=out_shapes,
        interpret=interpret)(xp, w4, bh, h0, c0)


def _run_fwd(xp, w4, bh, h0, c0, interpret):
    T, _, N, H = xp.shape
    out_shapes = [
        jax.ShapeDtypeStruct((T, N, H), jnp.float32),      # ys
        jax.ShapeDtypeStruct((T, 4, N, H), jnp.float32),   # gates
        jax.ShapeDtypeStruct((T, N, H), jnp.float32),      # cs
        jax.ShapeDtypeStruct((N, H), jnp.float32),         # hT
        jax.ShapeDtypeStruct((N, H), jnp.float32),         # cT
    ]
    return pl.pallas_call(
        functools.partial(_fwd_kernel, T), out_shape=out_shapes,
        interpret=interpret)(xp, w4, bh, h0, c0)


def _run_bwd(gates, cs, ys, w4, h0, c0, dys, dhT, dcT, interpret):
    T, _, N, H = gates.shape
    out_shapes = [
        jax.ShapeDtypeStruct((T, 4, N, H), jnp.float32),   # dxp
        jax.ShapeDtypeStruct((4, H, H), jnp.float32),      # dw4
        jax.ShapeDtypeStruct((4, H), jnp.float32),         # dbh
        jax.ShapeDtypeStruct((N, H), jnp.float32),         # dh0
        jax.ShapeDtypeStruct((N, H), jnp.float32),         # dc0
    ]
    return pl.pallas_call(
        functools.partial(_bwd_kernel, T), out_shape=out_shapes,
        interpret=interpret)(gates, cs, ys, w4, h0, c0, dys, dhT, dcT)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def lstm_seq(xp, w4, bh, h0, c0, interpret=False):
    """Fused LSTM over a whole sequence.

    xp: (T, 4, N, H) input-side projections (x@Wx + bx, gate-major);
    w4: (4, H, H) recurrent weights, w4[k] = (in, out) of gate k;
    bh: (4, H); h0/c0: (N, H).  Gate order i, f, g, o (the RNN op's
    split order).  Returns (ys (T,N,H), hT, cT).

    The primal (no gradient requested) runs the residual-free
    inference kernel; under ``jax.grad`` the vjp fwd saves
    gates/cell-states for the backward kernel.
    """
    ys, hT, cT = _run_infer(xp, w4, bh, h0, c0, interpret)
    return ys, hT, cT


def _vjp_fwd(xp, w4, bh, h0, c0, interpret):
    ys, gates, cs, hT, cT = _run_fwd(xp, w4, bh, h0, c0, interpret)
    return (ys, hT, cT), (gates, cs, ys, w4, h0, c0)


def _vjp_bwd(interpret, saved, grads):
    gates, cs, ys, w4, h0, c0 = saved
    dys, dhT, dcT = grads
    dxp, dw4, dbh, dh0, dc0 = _run_bwd(
        gates, cs, ys, w4, h0, c0, dys, dhT, dcT, interpret)
    return dxp, dw4, dbh, dh0, dc0


lstm_seq.defvjp(_vjp_fwd, _vjp_bwd)
