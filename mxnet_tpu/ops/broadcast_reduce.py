"""Broadcasting binary ops and reductions.

Census source: reference ``src/operator/tensor/elemwise_binary_broadcast_op*``
and ``broadcast_reduce_op_value.cc`` / ``broadcast_reduce_op_index.cc``
(SURVEY §2.3).  XLA broadcasts/reduces natively; the reference's explicit
broadcast-shape machinery collapses into jnp semantics.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .helpers import binary, simple
from .registry import REQUIRED, pbool, pfloat, pint, ptuple, register


def _axis_param(v):
    """axis: None | int | tuple-of-int; () means 'reduce all' (reference
    convention for the default axis=())"""
    if v is None or v == "None":
        return None
    if isinstance(v, str):
        import ast

        v = ast.literal_eval(v)
    if isinstance(v, (int, np.integer)):
        return (int(v),)
    t = tuple(int(x) for x in v)
    return t if t else None  # () -> reduce over everything


def _f(fn):
    def g(a, b):
        return fn(a, b).astype(a.dtype)

    return g


# -- broadcast binary -------------------------------------------------------
binary("broadcast_add", jnp.add, aliases=("broadcast_plus",))
binary("broadcast_sub", jnp.subtract, aliases=("broadcast_minus",))
binary("broadcast_mul", jnp.multiply)
binary("broadcast_div", jnp.divide)
binary("broadcast_power", jnp.power)
binary("broadcast_maximum", jnp.maximum)
binary("broadcast_minimum", jnp.minimum)
binary("broadcast_hypot", jnp.hypot)
binary("broadcast_equal", _f(jnp.equal))
binary("broadcast_not_equal", _f(jnp.not_equal))
binary("broadcast_greater", _f(jnp.greater))
binary("broadcast_greater_equal", _f(jnp.greater_equal))
binary("broadcast_lesser", _f(jnp.less))
binary("broadcast_lesser_equal", _f(jnp.less_equal))


# -- broadcast shape ops ----------------------------------------------------
def _broadcast_to(data, shape):
    # reference semantics: 0 in target shape keeps the input dim
    tgt = tuple(int(s) if int(s) != 0 else int(d) for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


simple("broadcast_to", _broadcast_to, params={"shape": (ptuple, REQUIRED)})


def _broadcast_axis(data, axis, size):
    tgt = list(data.shape)
    for ax, s in zip(axis, size):
        if data.shape[ax] != 1:
            raise ValueError("broadcast_axis: input dim %d must be 1" % ax)
        tgt[ax] = s
    return jnp.broadcast_to(data, tuple(tgt))


simple("broadcast_axis", _broadcast_axis,
       params={"axis": (ptuple, REQUIRED), "size": (ptuple, REQUIRED)},
       aliases=("broadcast_axes",))


# -- reductions -------------------------------------------------------------
def _reduce(fn, nan_to_num=None):
    def g(data, axis, keepdims, exclude=False):
        ax = axis
        if ax is not None and exclude:
            ax = tuple(i for i in range(data.ndim) if i not in
                       tuple(a % data.ndim for a in ax))
        x = data
        if nan_to_num is not None:
            x = jnp.where(jnp.isnan(x), jnp.asarray(nan_to_num, x.dtype), x)
        return fn(x, axis=ax, keepdims=keepdims)

    return g


_REDUCE_PARAMS = {
    "axis": (_axis_param, None),
    "keepdims": (pbool, False),
    "exclude": (pbool, False),
}

simple("sum", _reduce(jnp.sum), params=_REDUCE_PARAMS, aliases=("sum_axis",))
simple("mean", _reduce(jnp.mean), params=_REDUCE_PARAMS)
simple("prod", _reduce(jnp.prod), params=_REDUCE_PARAMS)
simple("nansum", _reduce(jnp.sum, nan_to_num=0.0), params=_REDUCE_PARAMS)
simple("nanprod", _reduce(jnp.prod, nan_to_num=1.0), params=_REDUCE_PARAMS)
simple("max", _reduce(jnp.max), params=_REDUCE_PARAMS, aliases=("max_axis",))
simple("min", _reduce(jnp.min), params=_REDUCE_PARAMS, aliases=("min_axis",))

# norm: reference 0.9.5 reduces ALL elements to shape (1,) L2 norm
# (``broadcast_reduce_op_value.cc`` norm).
simple("norm", lambda data: jnp.sqrt(jnp.sum(jnp.square(data))).reshape((1,)))


def _arg_reduce(fn):
    def g(data, axis, keepdims):
        if axis is None:
            res = fn(data.reshape(-1), axis=0)
            res = res.reshape((1,) * data.ndim) if keepdims else res
        else:
            res = fn(data, axis=axis)
            if keepdims:
                res = jnp.expand_dims(res, axis)
        return res.astype(data.dtype)

    return g


_ARG_PARAMS = {"axis": (lambda v: None if v in (None, "None") else pint(v), None),
               "keepdims": (pbool, False)}
simple("argmax", _arg_reduce(jnp.argmax), params=_ARG_PARAMS)
simple("argmin", _arg_reduce(jnp.argmin), params=_ARG_PARAMS)
simple("argmax_channel", lambda data: jnp.argmax(data, axis=1).astype(data.dtype))
