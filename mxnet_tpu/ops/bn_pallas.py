"""Fused train-mode BatchNorm as Pallas TPU kernels.

Reference: ``src/operator/batch_norm-inl.h`` / ``cudnn_batch_norm*`` compute
batch statistics with cuDNN's fused kernel; on TPU the XLA lowering of the
same math costs three HBM passes over the activation in the forward
(stats read, normalize read, write) and five in the backward (stats-grad
reads of x and dy, dx reads of x and dy, dx write).  At ResNet-50 b128 the
measured cost of batch statistics is ~26% of the whole training step
(``MXNET_BN_ABLATION=frozen`` ablation) — BN is the bandwidth hot spot the
round-2 profile pointed at.

These kernels cut the passes to the minimum:

* forward: ONE read of ``x`` — the per-lane-group block lives in VMEM,
  stats (f32, two-pass mean/variance) and normalization [+ optional fused
  ReLU] happen in-register, one write of ``y``.
* backward: ONE read each of ``x`` and ``dy`` — dgamma/dbeta reductions
  and the dx formula share the same VMEM residency, one write of ``dx``.

Layout (the part that actually matters on TPU): XLA assigns conv
activations a FEATURE-MINOR layout — ``bf16[N,C,H,W]{1,0,3,2}``, i.e.
physically ``[H][W][N][C]`` with the (8,128) tile on (N,C).  A Pallas
operand is constrained to the default row-major layout of its logical
shape, so a kernel over the logical NCHW (or a (N,C,S) flatten) forces a
relayout COPY of every activation in and out — measured net SLOWER than
no kernel at all.  Instead the wrapper views x as ``(H*W, N, C)`` via
transpose+reshape, whose row-major layout IS the physical layout: XLA
elides every copy (verified: zero ``copy`` ops in the compiled module).

The channel axis (lanes) is the grid: block = (S, N, L) with L = C when
C <= 128, else 128 (C must divide into 128-lane groups).  S and N stay
whole so each grid step owns its lanes' complete statistics.  Blocks are
admitted while S*N*L*itemsize fits MXNET_BN_PALLAS_BLOCK_BYTES (default
8 MB — ResNet stages at 14x14/7x7; the 56x56/28x28 stages exceed VMEM for
a 128-lane group and fall back to the XLA path).

Mosaic notes for this toolchain: 4D blocks with multi-axis reductions
SIGABRT the compiler, and in-kernel reshape of a loaded 4D vector is
unsupported — hence the 3D view with lane-preserving reductions over
(sublane, major) axes only, which compiles and runs.

The public entry is :func:`bn_train`, a ``jax.custom_vjp`` whose forward
returns ``(y, mean, var)``.  The mean/var outputs exist for the moving-stat
update, which the caller wraps in ``stop_gradient`` — the backward ignores
their (symbolically zero) cotangents.

Used by ``ops/nn.py`` ``_batch_norm`` (plain) and by the executor's
BN->ReLU peephole (``executor.py`` ``_graph_forward_plain``), which fuses
the activation into the kernel so the ReLU costs zero extra passes.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

# byte budget for one (S, N, L) input block; the kernels hold the block
# plus an f32 working set (~5x in the backward), which must clear the
# scoped-VMEM ceiling below
_BLOCK_BUDGET = int(os.environ.get("MXNET_BN_PALLAS_BLOCK_BYTES",
                                   str(8 * 1024 * 1024)))
# scoped-VMEM ceiling for the kernels (the toolchain default of 16 MB is
# too small for an 8 MB block plus its f32 working set)
_VMEM_LIMIT = int(os.environ.get("MXNET_BN_PALLAS_VMEM_BYTES",
                                 str(100 * 1024 * 1024)))


def _lane_group(c):
    """Lane-block size: full C up to 128 lanes, else 128-lane groups."""
    if c <= 128:
        return c
    return 128 if c % 128 == 0 else None


def _admissible(n, c, s, itemsize):
    lg = _lane_group(c)
    if lg is None:
        return None
    if s * n * lg * itemsize > _BLOCK_BUDGET:
        return None
    return lg


def _bn_fwd_kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, var_ref, *,
                   eps, fix_gamma, relu):
    xf = x_ref[...].astype(jnp.float32)            # (S, N, L)
    m = xf.shape[0] * xf.shape[1]
    mean = jnp.sum(xf, axis=(0, 1)) / m            # (L,)
    # two-pass variance: the block is already in VMEM, so the second pass
    # is free of HBM traffic and avoids E[x^2]-E[x]^2 cancellation
    ctr = xf - mean[None, None, :]
    var = jnp.sum(ctr * ctr, axis=(0, 1)) / m
    rstd = jax.lax.rsqrt(var + eps)
    if fix_gamma:
        scale = rstd
    else:
        scale = gamma_ref[0].astype(jnp.float32) * rstd
    shift = beta_ref[0].astype(jnp.float32) - mean * scale
    y = xf * scale[None, None, :] + shift[None, None, :]
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[0] = mean
    var_ref[0] = var


def _bn_bwd_kernel(x_ref, g_ref, mean_ref, var_ref, gamma_ref, beta_ref,
                   dx_ref, dgamma_ref, dbeta_ref, *, eps, fix_gamma, relu):
    xf = x_ref[...].astype(jnp.float32)            # (S, N, L)
    gf = g_ref[...].astype(jnp.float32)
    m = xf.shape[0] * xf.shape[1]
    mean = mean_ref[0]
    rstd = jax.lax.rsqrt(var_ref[0] + eps)
    xhat = (xf - mean[None, None, :]) * rstd[None, None, :]
    if fix_gamma:
        gamma = jnp.ones_like(mean)
    else:
        gamma = gamma_ref[0].astype(jnp.float32)
    if relu:
        # recompute the relu mask from the saved stats instead of saving
        # (or re-reading) the activation output
        shift = beta_ref[0].astype(jnp.float32) - mean * gamma * rstd
        pre = xf * (gamma * rstd)[None, None, :] + shift[None, None, :]
        gf = jnp.where(pre > 0.0, gf, 0.0)
    dbeta = jnp.sum(gf, axis=(0, 1))               # (L,)
    dgamma = jnp.sum(gf * xhat, axis=(0, 1))
    k = (gamma * rstd)[None, None, :]
    dx = k * (gf - dbeta[None, None, :] / m
              - xhat * dgamma[None, None, :] / m)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dgamma_ref[0] = dgamma
    dbeta_ref[0] = dbeta


def _pallas_mode():
    # Default OFF: benchmarked END-TO-END SLOWER than the XLA path on
    # ResNet-50 b128 (2081 vs 2215 img/s) — the eligible mid/late stages
    # lane-block a feature-minor array, so each (S, N, 128-lane) block is
    # a strided HBM read (256B bursts out of 2048B rows), and the early
    # stages don't fit a full-C block in VMEM at all.  XLA's own schedule
    # (2R+1W fwd, 4R+1W bwd, reductions fused multi-output) is already at
    # the streaming lower bound for HBM-resident activations.  Kept as an
    # opt-in ("1"/"auto") for toolchains/shapes where the tradeoff
    # differs, and "interpret" for CPU tests of the kernel math.
    return os.environ.get("MXNET_BN_PALLAS", "0")


def _on_tpu():
    """Device of the computation being traced: the executor/imperative
    dispatch sets ``registry.trace_device``; outside any such trace fall
    back to the process default backend."""
    from .registry import trace_device

    dev = trace_device.get()
    if dev is not None:
        return dev == "tpu"
    return jax.default_backend() == "tpu"


def eligible(x):
    """Whether the Pallas path applies for this input (trace-time)."""
    mode = _pallas_mode()
    if mode not in ("1", "auto", "interpret"):
        return False
    if mode != "interpret" and not _on_tpu():
        return False
    if x.ndim < 2:
        return False
    n, c = x.shape[0], x.shape[1]
    s = 1
    for d in x.shape[2:]:
        s *= d
    return _admissible(n, c, s, x.dtype.itemsize) is not None


def _bn_fwd_call(xt, gamma2, beta2, eps, fix_gamma, relu, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, n, c = xt.shape
    lg = _admissible(n, c, s, xt.dtype.itemsize)
    kernel = functools.partial(_bn_fwd_kernel, eps=eps,
                               fix_gamma=fix_gamma, relu=relu)
    y, mean, var = pl.pallas_call(
        kernel,
        grid=(c // lg,),
        in_specs=[
            pl.BlockSpec((s, n, lg), lambda i: (0, 0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((s, n, lg), lambda i: (0, 0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, n, c), xt.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(xt, gamma2, beta2)
    return y, mean, var


def _bn_bwd_call(xt, gt, mean2, var2, gamma2, beta2, eps, fix_gamma, relu,
                 interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, n, c = xt.shape
    lg = _admissible(n, c, s, xt.dtype.itemsize)
    kernel = functools.partial(_bn_bwd_kernel, eps=eps,
                               fix_gamma=fix_gamma, relu=relu)
    dx, dgamma, dbeta = pl.pallas_call(
        kernel,
        grid=(c // lg,),
        in_specs=[
            pl.BlockSpec((s, n, lg), lambda i: (0, 0, i)),
            pl.BlockSpec((s, n, lg), lambda i: (0, 0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((s, n, lg), lambda i: (0, 0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
            pl.BlockSpec((1, lg), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, n, c), xt.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(xt, gt, mean2, var2, gamma2, beta2)
    return dx, dgamma, dbeta


@functools.lru_cache(maxsize=None)
def _bn_fused_fn(eps, fix_gamma, relu, interpret):
    @jax.custom_vjp
    def f(xt, gamma2, beta2):
        return _bn_fwd_call(xt, gamma2, beta2, eps, fix_gamma, relu,
                            interpret)

    def fwd(xt, gamma2, beta2):
        y, mean, var = _bn_fwd_call(xt, gamma2, beta2, eps, fix_gamma,
                                    relu, interpret)
        return (y, mean, var), (xt, gamma2, beta2, mean, var)

    def bwd(res, cts):
        xt, gamma2, beta2, mean, var = res
        gy, _gmean, _gvar = cts
        # mean/var feed only the stop_gradient'd moving-stat update — their
        # cotangents are symbolically zero (the caller guarantees this by
        # excluding output_mean_var graphs from the Pallas path)
        dx, dgamma, dbeta = _bn_bwd_call(
            xt, gy, mean, var, gamma2, beta2, eps, fix_gamma, relu,
            interpret)
        if fix_gamma:
            dgamma = jnp.zeros_like(dgamma)
        return (dx, dgamma.astype(gamma2.dtype),
                dbeta.astype(beta2.dtype))

    f.defvjp(fwd, bwd)
    return f


def bn_train(x, gamma, beta, eps, fix_gamma, relu=False):
    """Fused train-mode BN over NC[spatial] ``x``; returns
    ``(y, mean, var)`` with mean/var of shape (C,).  Caller must have
    checked :func:`eligible`.

    The kernel sees the layout-native (S, N, C) view (see module
    docstring); the transpose/reshape pair on each side is a bitcast
    against the activations' physical feature-minor layout, so no data
    moves outside the kernel itself.
    """
    n, c = x.shape[0], x.shape[1]
    spatial_axes = tuple(range(2, x.ndim))
    s = 1
    for d in x.shape[2:]:
        s *= d
    xt = x.transpose(spatial_axes + (0, 1)).reshape(s, n, c)
    interpret = _pallas_mode() == "interpret" or not _on_tpu()
    f = _bn_fused_fn(float(eps), bool(fix_gamma), bool(relu), interpret)
    y, mean, var = f(xt, gamma.reshape(1, c), beta.reshape(1, c))
    y = y.reshape(x.shape[2:] + (n, c)).transpose(
        (x.ndim - 2, x.ndim - 1) + tuple(range(x.ndim - 2)))
    return y, mean.reshape(c), var.reshape(c)
