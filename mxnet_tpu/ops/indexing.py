"""Indexing, ordering, sampling and init ops.

Census source: reference ``src/operator/tensor/indexing_op.cc`` (Embedding/
take/batch_take/one_hot), ``ordering_op.cc`` (topk/sort/argsort),
``sample_op.cc`` (uniform/normal), ``init_op.cc`` (zeros/ones/arange/
ones_like) — SURVEY §2.3.

Sampling ops are the only rng consumers here: they take the rng key the
runtime threads through (imperative: global `mx.random` state; symbolic:
per-call key from the executor).  Gather/one-hot stay XLA-native so they fuse;
sort/topk lower to XLA's sort HLO.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .helpers import simple
from .registry import (REQUIRED, np_dtype, pbool, pdtype, pfloat, pint, pstr,
                       ptuple, register)


def _opt_int(v):
    return None if v in (None, "None") else pint(v)


# -- indexing ---------------------------------------------------------------
def _embedding(data, weight, input_dim, output_dim, dtype):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


simple("Embedding", _embedding, arguments=("data", "weight"),
       params={"input_dim": (pint, REQUIRED), "output_dim": (pint, REQUIRED),
               "dtype": (pdtype, "float32")})

simple("take", lambda a, indices, axis, mode: jnp.take(
    a, indices.astype(jnp.int32), axis=axis,
    mode={"clip": "clip", "wrap": "wrap"}.get(mode, "clip")),
    arguments=("a", "indices"),
    params={"axis": (pint, 0), "mode": (pstr, "clip")})


def _batch_take(a, indices):
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32).reshape(-1, 1), axis=1).reshape(-1)


simple("batch_take", _batch_take, arguments=("a", "indices"))


def _one_hot(indices, depth, on_value, off_value, dtype):
    dt = np_dtype(dtype)
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dt)
    return oh * jnp.asarray(on_value, dt) + (1 - oh) * jnp.asarray(off_value, dt)


simple("one_hot", _one_hot, arguments=("indices",),
       params={"depth": (pint, REQUIRED), "on_value": (pfloat, 1.0),
               "off_value": (pfloat, 0.0), "dtype": (pdtype, "float32")})


def _fill_element_0index(lhs, mhs, rhs):
    """lhs[i, rhs[i]] = mhs[i] (legacy NDArray fn, ``ndarray.cc:748-867``)."""
    idx = rhs.astype(jnp.int32)
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, idx].set(mhs)


simple("fill_element_0index", _fill_element_0index, arguments=("lhs", "mhs", "rhs"))


# -- ordering ---------------------------------------------------------------
def _topk(data, axis, k, ret_typ, is_ascend):
    ax = axis if axis is not None else data.ndim - 1
    k = k if k > 0 else data.shape[ax]
    src = data if not is_ascend else -data
    moved = jnp.moveaxis(src, ax, -1)
    vals, idxs = jax.lax.top_k(moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax)
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idxs.astype(data.dtype)
    if ret_typ == "mask":
        onehots = jax.nn.one_hot(jnp.moveaxis(idxs, ax, -1), moved.shape[-1],
                                 dtype=data.dtype).sum(-2)
        return jnp.moveaxis(onehots, -1, ax)
    raise ValueError("topk: bad ret_typ %r" % ret_typ)


def _topk_apply(attrs, inputs, aux, is_train, rng):
    res = _topk(inputs[0], attrs["axis"], attrs["k"], attrs["ret_typ"],
                attrs["is_ascend"])
    if attrs["ret_typ"] == "both":
        ax = attrs["axis"] if attrs["axis"] is not None else inputs[0].ndim - 1
        # recompute both halves
        vals = _topk(inputs[0], attrs["axis"], attrs["k"], "value", attrs["is_ascend"])
        idxs = _topk(inputs[0], attrs["axis"], attrs["k"], "indices", attrs["is_ascend"])
        return [vals, idxs]
    return [res]


register("topk", _topk_apply,
         outputs=lambda attrs: ["output", "indices"] if attrs["ret_typ"] == "both"
         else ["output"],
         params={"axis": (_opt_int, -1), "k": (pint, 1),
                 "ret_typ": (pstr, "indices"), "is_ascend": (pbool, False)})


def _sort(data, axis, is_ascend):
    s = jnp.sort(data, axis=axis)
    return s if is_ascend else jnp.flip(s, axis=axis if axis is not None else 0)


simple("sort", _sort, params={"axis": (_opt_int, -1), "is_ascend": (pbool, True)})


def _argsort(data, axis, is_ascend):
    s = jnp.argsort(data, axis=axis)
    if not is_ascend:
        s = jnp.flip(s, axis=axis if axis is not None else 0)
    return s.astype(data.dtype)


simple("argsort", _argsort, params={"axis": (_opt_int, -1), "is_ascend": (pbool, True)})


# -- sampling ---------------------------------------------------------------
def _sample_uniform(attrs, inputs, aux, is_train, rng):
    dt = np_dtype(attrs["dtype"])
    return [jax.random.uniform(rng, attrs["shape"], dtype=dt,
                               minval=attrs["low"], maxval=attrs["high"])]


register("_sample_uniform", _sample_uniform, arguments=(), needs_rng=True,
         params={"low": (pfloat, 0.0), "high": (pfloat, 1.0),
                 "shape": (ptuple, (1,)), "dtype": (pdtype, "float32")},
         aliases=("uniform", "_random_uniform"))


def _sample_normal(attrs, inputs, aux, is_train, rng):
    dt = np_dtype(attrs["dtype"])
    return [attrs["loc"] + attrs["scale"]
            * jax.random.normal(rng, attrs["shape"], dtype=dt)]


register("_sample_normal", _sample_normal, arguments=(), needs_rng=True,
         params={"loc": (pfloat, 0.0), "scale": (pfloat, 1.0),
                 "shape": (ptuple, (1,)), "dtype": (pdtype, "float32")},
         aliases=("normal", "_random_normal"))


# -- init ops ---------------------------------------------------------------
def _init_params():
    return {"shape": (ptuple, REQUIRED), "dtype": (pdtype, "float32")}


simple("_zeros", lambda shape, dtype: jnp.zeros(shape, np_dtype(dtype)),
       arguments=(), params=_init_params())
simple("_ones", lambda shape, dtype: jnp.ones(shape, np_dtype(dtype)),
       arguments=(), params=_init_params())


def _arange(start, stop, step, repeat, dtype):
    a = jnp.arange(start, stop, step, dtype=np_dtype(dtype))
    return jnp.repeat(a, repeat) if repeat > 1 else a


simple("_arange", _arange, arguments=(),
       params={"start": (pfloat, 0.0),
               "stop": (lambda v: None if v in (None, "None") else pfloat(v), None),
               "step": (pfloat, 1.0), "repeat": (pint, 1),
               "dtype": (pdtype, "float32")})

simple("ones_like", jnp.ones_like)
simple("zeros_like", jnp.zeros_like)
simple("_identity_with_attr_like_rhs", lambda lhs, rhs: lhs,
       arguments=("lhs", "rhs"))
