"""Torch interop ops — the ``plugin/torch`` analog.

Reference: ``plugin/torch/torch_module-inl.h`` / ``torch_criterion-inl.h``
register ``TorchModule``/``TorchCriterion`` ops whose ``lua_string`` attr
names a (Lua)Torch module; its parameters become learnable graph arguments
and forward/backward dispatch into the Torch runtime.

TPU-native: the attr holds a **PyTorch** module expression (e.g.
``"nn.Linear(4, 3)"`` — evaluated with ``nn``/``torch`` in scope, the same
user-authored-code trust model as the reference's Lua string).  The module
runs on the host CPU via ``jax.pure_callback`` (like ``Custom`` ops), its
parameters are exposed as graph arguments so the framework's optimizers
train them, and backward routes through torch autograd via
``jax.custom_vjp``.  Composes with jit and the fused executor graph.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import REQUIRED, pint, pstr, register


def _torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is baked in
        raise MXNetError("TorchModule requires pytorch") from e
    return torch


_MODULE_CACHE = {}  # expr string -> instantiated torch.nn.Module


def _build(expr):
    if expr not in _MODULE_CACHE:
        torch = _torch()
        import torch.nn as nn

        mod = eval(expr, {"nn": nn, "torch": torch})  # noqa: S307
        if not isinstance(mod, nn.Module):
            raise MXNetError(
                "TorchModule: %r did not evaluate to a torch.nn.Module" % expr)
        _MODULE_CACHE[expr] = mod.eval().float()
    return _MODULE_CACHE[expr]


def _param_items(mod):
    return [(n, p) for n, p in mod.named_parameters()]


def _module_arguments(attrs):
    mod = _build(attrs["lua_string"])
    n_data = attrs["num_data"]
    return ["data_%d" % i for i in range(n_data)] + \
        ["param_%s" % n.replace(".", "_") for n, _ in _param_items(mod)]


def _run_functional(mod, names, param_tensors, data_tensors, is_train=False):
    from torch.func import functional_call

    pdict = {n: t for n, t in zip(names, param_tensors)}
    # detached buffer copies keep the call pure: train-mode modules (BN)
    # mutate the copies, never the cached module — torch aux state is not
    # tracked into the graph and stays at its init statistics
    pdict.update({n: b.detach().clone() for n, b in mod.named_buffers()})
    # honor train/eval mode (dropout etc.)
    was_training = mod.training
    mod.train(bool(is_train))
    try:
        out = functional_call(mod, pdict, tuple(data_tensors))
    finally:
        mod.train(was_training)
    return list(out) if isinstance(out, (tuple, list)) else [out]


def _torch_module_apply(attrs, inputs, aux, is_train, rng):
    torch = _torch()
    mod = _build(attrs["lua_string"])
    n_data = attrs["num_data"]
    n_out = attrs["num_outputs"]
    names = [n for n, _ in _param_items(mod)]
    if attrs["num_params"] >= 0 and attrs["num_params"] != len(names):
        raise MXNetError(
            "TorchModule %r: num_params=%d but module has %d parameters"
            % (attrs["lua_string"], attrs["num_params"], len(names)))
    in_specs = [jax.ShapeDtypeStruct(tuple(x.shape), jnp.float32)
                for x in inputs]

    # output shapes: run the torch module once on host zeros at trace time
    with torch.no_grad():
        dummy = [torch.zeros(tuple(x.shape)) for x in inputs[:n_data]]
        params = [torch.zeros(tuple(x.shape)) for x in inputs[n_data:]]
        douts = _run_functional(mod, names, params, dummy)
    if len(douts) != n_out:
        raise MXNetError("TorchModule %r: produced %d outputs, declared "
                         "num_outputs=%d" % (attrs["lua_string"], len(douts),
                                             n_out))
    out_specs = [jax.ShapeDtypeStruct(tuple(o.shape), jnp.float32)
                 for o in douts]

    def host_forward(seed, *tensors):
        # same torch seed in forward and backward: stochastic modules
        # (dropout) draw identical masks in both passes
        torch.manual_seed(int(np.asarray(seed).ravel()[0]))
        with torch.no_grad():
            data = [torch.from_numpy(np.array(t, np.float32))
                    for t in tensors[:n_data]]
            ps = [torch.from_numpy(np.array(t, np.float32))
                  for t in tensors[n_data:]]
            outs = _run_functional(mod, names, ps, data, is_train)
        return tuple(o.numpy() for o in outs)

    def host_backward(seed, *tensors):
        torch.manual_seed(int(np.asarray(seed).ravel()[0]))
        cots = tensors[:n_out]
        data = [torch.from_numpy(np.array(t, np.float32))
                .requires_grad_(True) for t in tensors[n_out:n_out + n_data]]
        ps = [torch.from_numpy(np.array(t, np.float32))
              .requires_grad_(True) for t in tensors[n_out + n_data:]]
        outs = _run_functional(mod, names, ps, data, is_train=is_train)
        torch.autograd.backward(
            outs, [torch.from_numpy(np.array(c, np.float32))
                   for c in cots])
        return tuple((x.grad if x.grad is not None
                      else torch.zeros_like(x)).numpy() for x in data + ps)

    @jax.custom_vjp
    def run(seed, ins):
        res = jax.pure_callback(host_forward, tuple(out_specs), seed, *ins)
        return list(res)

    def run_fwd(seed, ins):
        return run(seed, ins), (seed, ins)

    def run_bwd(resid, cots):
        seed, ins = resid
        grads = jax.pure_callback(host_backward, tuple(in_specs),
                                  seed, *cots, *ins)
        return (jnp.zeros_like(seed), list(grads))

    run.defvjp(run_fwd, run_bwd)
    f32 = [x.astype(jnp.float32) for x in inputs]
    seed = (rng if rng is not None else jnp.zeros(2, jnp.uint32))
    return [o.astype(inputs[0].dtype) for o in run(seed, f32)]


register(
    "TorchModule", _torch_module_apply,
    arguments=_module_arguments,
    outputs=lambda attrs: ["output_%d" % i
                           for i in range(attrs["num_outputs"])],
    params={"lua_string": (pstr, REQUIRED), "num_data": (pint, 1),
            "num_params": (pint, -1), "num_outputs": (pint, 1)},
    needs_rng=True,
    doc="Run a torch.nn module as a graph op "
        "(reference plugin/torch/torch_module-inl.h)",
)


def _torch_criterion_apply(attrs, inputs, aux, is_train, rng):
    torch = _torch()
    crit = _build(attrs["lua_string"])
    data_spec = jax.ShapeDtypeStruct(tuple(inputs[0].shape), jnp.float32)

    # loss shape at trace time from a dummy run — scalar criteria give (1,),
    # reduction='none' criteria keep their per-element shape
    with torch.no_grad():
        dummy = crit(torch.zeros(tuple(inputs[0].shape)),
                     torch.zeros(tuple(inputs[1].shape)))
    out_shape = tuple(dummy.shape) if dummy.dim() > 0 else (1,)
    out_spec = jax.ShapeDtypeStruct(out_shape, jnp.float32)

    def host_forward(d, l):
        with torch.no_grad():
            loss = crit(torch.from_numpy(np.array(d, np.float32)),
                        torch.from_numpy(np.array(l, np.float32)))
        return np.asarray(loss.numpy(), np.float32).reshape(out_shape)

    def host_backward(cot, d, l):
        dt = torch.from_numpy(
            np.array(d, np.float32)).requires_grad_(True)
        loss = crit(dt, torch.from_numpy(np.array(l, np.float32)))
        loss.backward(torch.from_numpy(np.array(cot, np.float32))
                      .reshape(tuple(loss.shape)))
        return dt.grad.numpy()

    @jax.custom_vjp
    def run(d, l):
        return jax.pure_callback(host_forward, out_spec, d, l)

    def run_fwd(d, l):
        return run(d, l), (d, l)

    def run_bwd(resid, cot):
        d, l = resid
        g = jax.pure_callback(host_backward, data_spec, cot, d, l)
        return (g, jnp.zeros_like(l))

    run.defvjp(run_fwd, run_bwd)
    out = run(inputs[0].astype(jnp.float32), inputs[1].astype(jnp.float32))
    return [out.astype(inputs[0].dtype)]


register(
    "TorchCriterion", _torch_criterion_apply,
    arguments=("data", "label"),
    params={"lua_string": (pstr, REQUIRED)},
    doc="Torch loss module as a graph op "
        "(reference plugin/torch/torch_criterion-inl.h)",
)


# backward (argument) shape inference: parameter shapes come from the torch
# module itself, so simple_bind works with only the data shape given
def _torch_module_infer(attrs, ins, dts, auxs):
    mod = _build(attrs["lua_string"])
    n_data = attrs["num_data"]
    for i, (_, p) in enumerate(_param_items(mod)):
        if ins[n_data + i] is None:
            ins[n_data + i] = tuple(p.shape)
    return ins, auxs


from .registry import get  # noqa: E402

get("TorchModule").infer_inputs = _torch_module_infer
