"""Neural-net layer ops (the legacy-registry census, SURVEY §2.3).

Reference kernels: ``src/operator/{fully_connected,convolution,pooling,
batch_norm,activation,dropout,concat,slice_channel,pad,lrn,instance_norm,
l2_normalization,upsampling,swapaxis,leaky_relu,sequence_*}-inl.h``.

TPU design: none of these are hand kernels — Convolution/FullyConnected lower
to XLA conv/dot_general (MXU), BatchNorm/Pooling/activations are XLA
elementwise/reduce-window that fuse around them.  The reference's
im2col+GEMM (``src/operator/nn/im2col.h``) and cuDNN dispatch disappear:
XLA picks the conv algorithm.  Layout is NCHW to match the reference API;
XLA relayouts internally for the MXU.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from . import bn_pallas
from .helpers import acc_dtype as _acc_dtype, simple
from .registry import (REQUIRED, pbool, pfloat, pint, pstr, ptuple, register)


@lru_cache(maxsize=None)
def _conv_f32acc(stride, padding, lhs_dilation, rhs_dilation, dn, groups):
    """Conv whose primal accumulates f32 for low-precision inputs (the
    reference's cuDNN conv accumulates f32; bf16 partials would drift
    top-1), output cast back to the input dtype.

    JAX 0.9's conv transpose rule rejects the mixed-dtype cotangent that
    ``preferred_element_type`` + ``astype`` produces, so the backward is a
    custom_vjp that casts the cotangent to the primal dtype and reuses the
    plain same-dtype conv vjp (whose grad convs still accumulate f32
    inside the MXU)."""
    kw = dict(window_strides=stride, padding=padding,
              lhs_dilation=lhs_dilation, rhs_dilation=rhs_dilation,
              dimension_numbers=dn, feature_group_count=groups)

    def plain(data, weight):
        return jax.lax.conv_general_dilated(data, weight, **kw)

    @jax.custom_vjp
    def conv(data, weight):
        return jax.lax.conv_general_dilated(
            data, weight, preferred_element_type=_acc_dtype(data.dtype),
            **kw).astype(data.dtype)

    def fwd(data, weight):
        return conv(data, weight), (data, weight)

    def bwd(res, g):
        data, weight = res
        _, vjp = jax.vjp(plain, data, weight)
        # the barrier keeps XLA:TPU from fusing a pad/slice-produced
        # cotangent into the transposed convs — that fusion miscompiles
        # on the current TPU toolchain (wrong data-gradients for any
        # Pad/Crop/slice directly after a conv; verified against CPU and
        # finite differences).  MXNET_CONV_GRAD_BARRIER=0 disables it for
        # toolchains without the bug.
        g = g.astype(data.dtype)
        import os

        if os.environ.get("MXNET_CONV_GRAD_BARRIER", "1") != "0":
            g = jax.lax.optimization_barrier(g)
        return vjp(g)

    conv.defvjp(fwd, bwd)
    return conv


def _norm_stp(kernel, stride, dilate, pad):
    nd = len(kernel)
    stride = tuple(stride) if stride else (1,) * nd
    dilate = tuple(dilate) if dilate else (1,) * nd
    pad = tuple(pad) if pad else (0,) * nd
    return stride, dilate, pad


# ---------------------------------------------------------------------------
# FullyConnected — reference ``fully_connected-inl.h:47-81`` (mshadow dot)
# ---------------------------------------------------------------------------
def _fully_connected(attrs, inputs, aux, is_train, rng):
    data = inputs[0]
    weight = inputs[1]
    data = _match_param_dtype(data, weight)
    if attrs["flatten"] and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = jnp.dot(data, weight.T,
                  preferred_element_type=_acc_dtype(data.dtype)).astype(data.dtype)
    if not attrs["no_bias"]:
        out = out + inputs[2]
    return [out]


register("FullyConnected", _fully_connected,
         arguments=lambda a: ["data", "weight"] + ([] if a["no_bias"] else ["bias"]),
         params={"num_hidden": (pint, REQUIRED), "no_bias": (pbool, False),
                 "flatten": (pbool, True)},
         hint="fullyconnected")


# ---------------------------------------------------------------------------
# Convolution — reference ``convolution-inl.h`` (im2col+GEMM) / cuDNN.
# N-D (1/2/3): XLA conv_general_dilated on NC[DHW] layouts.
# ---------------------------------------------------------------------------
_CONV_DIMNUMS = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
                 3: ("NCDHW", "OIDHW", "NCDHW")}


def _match_param_dtype(data, weight):
    """Mixed precision: the parameter dtype defines the net's compute
    precision (the reference's fp16-net pattern casts data at the input),
    so f32 iterator data into a bf16-cast net runs — and stays — bf16."""
    if data.dtype != weight.dtype:
        data = data.astype(weight.dtype)
    return data


def _stem_s2d_conv(data, weight):
    """EXACT rewrite of the 7x7/stride-2/pad-3 few-channel stem conv as
    space-to-depth(2x2) + 4x4/stride-1 conv (the MLPerf TPU ResNet stem
    transform).  C_in=3 wastes the MXU's 128-wide contraction lanes; the
    rewrite contracts over C*4=12 channels with 16 taps instead of 3
    with 49 — measured ~2x on the stem cluster (fwd+dgrad+wgrad).  Same
    weights, same math: tap p=2a+b of the 7x7 kernel (zero-padded to
    8x8) becomes block-tap a, in-block offset b of a 4x4 kernel over
    2x2-blocked input; outputs are bit-identical shapes.

    Reference analog: none — cuDNN handled the stem natively
    (``cudnn_convolution``); this is the TPU-first equivalent.
    """
    import jax.numpy as jnp

    n, c, h, w = data.shape
    k = weight.shape[0]
    xp = jnp.pad(data, ((0, 0), (0, 0), (3, 3), (3, 3)))
    hb, wb = (h + 6) // 2, (w + 6) // 2
    xb = xp.reshape(n, c, hb, 2, wb, 2)
    xb = xb.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * 4, hb, wb)
    wp = jnp.pad(weight, ((0, 0), (0, 0), (0, 1), (0, 1)))
    wb4 = wp.reshape(k, c, 4, 2, 4, 2)
    wb4 = wb4.transpose(0, 1, 3, 5, 2, 4).reshape(k, c * 4, 4, 4)
    return _conv_f32acc((1, 1), ((0, 0), (0, 0)), (1, 1), (1, 1),
                        _CONV_DIMNUMS[2], 1)(xb, wb4)


def _convolution(attrs, inputs, aux, is_train, rng):
    data, weight = inputs[0], inputs[1]
    data = _match_param_dtype(data, weight)
    kernel = attrs["kernel"]
    nd = len(kernel)
    stride, dilate, pad = _norm_stp(kernel, attrs["stride"], attrs["dilate"],
                                    attrs["pad"])
    import os as _os

    if (_os.environ.get("MXNET_CONV_STEM_S2D", "1") != "0"
            and nd == 2 and tuple(kernel) == (7, 7)
            and stride == (2, 2) and pad == (3, 3) and dilate == (1, 1)
            and attrs["num_group"] == 1 and data.shape[1] <= 4
            and data.shape[2] % 2 == 0 and data.shape[3] % 2 == 0):
        out = _stem_s2d_conv(data, weight)
    else:
        out = _conv_f32acc(stride, tuple((p, p) for p in pad), (1,) * nd,
                           dilate, _CONV_DIMNUMS[nd],
                           attrs["num_group"])(data, weight)
    if not attrs["no_bias"]:
        bias = inputs[2].reshape((1, -1) + (1,) * nd)
        out = out + bias
    return [out]


_CONV_PARAMS = {
    "kernel": (ptuple, REQUIRED), "stride": (ptuple, ()), "dilate": (ptuple, ()),
    "pad": (ptuple, ()), "num_filter": (pint, REQUIRED), "num_group": (pint, 1),
    "workspace": (pint, 1024), "no_bias": (pbool, False),
    "cudnn_tune": (pstr, None), "cudnn_off": (pbool, False),
    "layout": (pstr, None),
}

register("Convolution", _convolution,
         arguments=lambda a: ["data", "weight"] + ([] if a["no_bias"] else ["bias"]),
         params=_CONV_PARAMS, hint="convolution")


def _deconvolution(attrs, inputs, aux, is_train, rng):
    data, weight = inputs[0], inputs[1]
    data = _match_param_dtype(data, weight)
    kernel = attrs["kernel"]
    nd = len(kernel)
    stride, dilate, pad = _norm_stp(kernel, attrs["stride"], attrs["dilate"],
                                    attrs["pad"])
    adj = tuple(attrs["adj"]) if attrs["adj"] else (0,) * nd
    # Transposed conv = lhs-dilated conv with spatially-flipped kernel;
    # weight layout is (C_in, C_out/g, *k) = IOHW, matching the reference's
    # deconvolution weight shape.
    flip = (slice(None), slice(None)) + (slice(None, None, -1),) * nd
    padding = [(k - 1 - p, k - 1 - p + a)
               for k, p, a in zip(kernel, pad, adj)]
    dn = {1: ("NCH", "IOH", "NCH"), 2: ("NCHW", "IOHW", "NCHW"),
          3: ("NCDHW", "IODHW", "NCDHW")}[nd]
    out = _conv_f32acc(tuple((1,) * nd), tuple(padding), stride, dilate,
                       dn, attrs["num_group"])(data, weight[flip])
    if not attrs["no_bias"]:
        out = out + inputs[2].reshape((1, -1) + (1,) * nd)
    return [out]


register("Deconvolution", _deconvolution,
         arguments=lambda a: ["data", "weight"] + ([] if a["no_bias"] else ["bias"]),
         params={**_CONV_PARAMS, "adj": (ptuple, ()), "target_shape": (ptuple, ())},
         hint="deconvolution")


# ---------------------------------------------------------------------------
# Pooling — reference ``pooling-inl.h`` + ``nn/pool.h``; reduce_window on TPU
# ---------------------------------------------------------------------------
def _pool_out_dim(x, k, p, s, convention):
    if convention == "full":
        return int(np.ceil(float(x + 2 * p - k) / s)) + 1
    return int(np.floor(float(x + 2 * p - k) / s)) + 1


def _pooling(attrs, inputs, aux, is_train, rng):
    data = inputs[0]
    nd = data.ndim - 2
    if attrs["global_pool"]:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = attrs["kernel"]
        stride, _, pad = _norm_stp(kernel, attrs["stride"], (), attrs["pad"])
    # 'full' convention (ceil) may need extra right-padding
    extra = []
    for i in range(nd):
        o = _pool_out_dim(data.shape[2 + i], kernel[i], pad[i], stride[i],
                          attrs["pooling_convention"] if not attrs["global_pool"]
                          else "valid")
        need = (o - 1) * stride[i] + kernel[i] - data.shape[2 + i] - pad[i]
        extra.append(max(need, pad[i]))
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0)) + tuple((p, e) for p, e in zip(pad, extra))
    pt = attrs["pool_type"]
    if pt == "max":
        # literal -inf init so JAX recognises the differentiable
        # reduce-window-max pattern (select-and-scatter transpose)
        out = jax.lax.reduce_window(data, -jnp.inf, jax.lax.max,
                                    window, strides, padding)
    elif pt in ("avg", "sum"):
        out = jax.lax.reduce_window(data, 0.0, jax.lax.add,
                                    window, strides, padding)
        if pt == "avg":
            # reference counts the full window incl. padding (mshadow pool)
            out = out / float(np.prod(kernel))
    else:
        raise MXNetError("Pooling: bad pool_type %r" % pt)
    return [out]


register("Pooling", _pooling,
         params={"kernel": (ptuple, ()), "pool_type": (pstr, "max"),
                 "global_pool": (pbool, False), "stride": (ptuple, ()),
                 "pad": (ptuple, ()), "pooling_convention": (pstr, "valid")},
         aliases=("Pooling_v1",), hint="pooling")


# ---------------------------------------------------------------------------
# Activation / LeakyReLU / SoftmaxActivation
# ---------------------------------------------------------------------------
_ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
}


def _activation(attrs, inputs, aux, is_train, rng):
    return [_ACTS[attrs["act_type"]](inputs[0])]


register("Activation", _activation,
         params={"act_type": (pstr, REQUIRED)}, hint="activation")


def _leaky_relu(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    t = attrs["act_type"]
    if t == "leaky":
        return [jnp.where(x > 0, x, attrs["slope"] * x)]
    if t == "elu":
        return [jnp.where(x > 0, x, attrs["slope"] * jnp.expm1(x))]
    if t == "prelu":
        gamma = inputs[1].reshape((1, -1) + (1,) * (x.ndim - 2))
        return [jnp.where(x > 0, x, gamma * x)]
    if t == "rrelu":
        lo, up = attrs["lower_bound"], attrs["upper_bound"]
        if is_train:
            slope = jax.random.uniform(rng, x.shape, dtype=x.dtype,
                                       minval=lo, maxval=up)
        else:
            slope = jnp.asarray((lo + up) / 2.0, x.dtype)
        return [jnp.where(x > 0, x, slope * x)]
    raise MXNetError("LeakyReLU: bad act_type %r" % t)


register("LeakyReLU", _leaky_relu,
         arguments=lambda a: ["data", "gamma"] if a["act_type"] == "prelu"
         else ["data"],
         params={"act_type": (pstr, "leaky"), "slope": (pfloat, 0.25),
                 "lower_bound": (pfloat, 0.125), "upper_bound": (pfloat, 0.334)},
         needs_rng=True, hint="leakyrelu")


def _softmax_activation(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    if attrs["mode"] == "channel":
        return [jax.nn.softmax(x, axis=1)]
    return [jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)]


register("SoftmaxActivation", _softmax_activation,
         params={"mode": (pstr, "instance")}, hint="softmaxactivation")


# ---------------------------------------------------------------------------
# BatchNorm — reference ``batch_norm-inl.h`` / cudnn_batch_norm.
# aux moving_mean/moving_var updated in train mode (functional aux-update).
# ---------------------------------------------------------------------------
def _batch_norm(attrs, inputs, aux, is_train, rng, act_type=None):
    """``act_type="relu"`` fuses the activation into the Pallas kernel —
    set only by the executor's BN->ReLU peephole (the registered op always
    passes None)."""
    x, gamma, beta = inputs
    moving_mean, moving_var = aux
    red = (0,) + tuple(range(2, x.ndim))
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    import os as _os

    bn_mode = _os.environ.get("MXNET_BN_ABLATION", "")
    if bn_mode == "frozen":  # perf-ablation only: skip batch statistics
        use_batch = False
    else:
        use_batch = is_train and not attrs["use_global_stats"]
    if use_batch and not attrs["output_mean_var"] \
            and bn_pallas.eligible(x):
        # fused single-HBM-pass BN (+ReLU): see ops/bn_pallas.py
        out, mean, var = bn_pallas.bn_train(
            x, gamma, beta, attrs["eps"], attrs["fix_gamma"],
            relu=(act_type == "relu"))
        m = attrs["momentum"]
        new_mean = moving_mean * m + jax.lax.stop_gradient(mean) * (1 - m)
        new_var = moving_var * m + jax.lax.stop_gradient(var) * (1 - m)
        return [out], [new_mean, new_var]
    if use_batch:
        # Stats ACCUMULATE in f32 always; what varies is the dtype of the
        # elementwise read pass.  For bf16 activations the read stays
        # bf16 (opt out: MXNET_BN_STATS_F32=1): materializing x.astype
        # (f32) made XLA emit a second full-size f32 copy of every conv
        # output as a fusion epilogue (+wider reduce reads) — measured
        # ~4 ms/step of pure bandwidth on ResNet-50 b128 (per-HLO
        # profile, tools/perf/step_profile.py).  The probe-shift below
        # bounds the bf16 rounding of d to ~2^-8 relative of the
        # *deviation*, and round-to-nearest is unbiased, so the
        # batch-mean/var error vanishes as 1/sqrt(N) — validated by the
        # bf16 convergence-parity harness.
        keep_bf16 = (x.dtype == jnp.bfloat16
                     and _os.environ.get("MXNET_BN_STATS_F32", "0") != "1")
        xf = x if keep_bf16 else x.astype(jnp.float32)
        # shifted single-pass variance: center on a per-channel probe
        # (first element, gradient-stopped — the shifts cancel exactly in
        # mean and var) so E[d^2]-E[d]^2 cancels catastrophically only
        # when |mean-probe| >> std, not |mean| >> std (raw 0-255 inputs)
        probe = jax.lax.stop_gradient(
            xf[(0, slice(None)) + (0,) * (x.ndim - 2)])
        d = xf - probe.reshape(bshape)
        cnt = 1
        for ax in red:
            cnt *= x.shape[ax]
        mean_d = jnp.sum(d, axis=red, dtype=jnp.float32) / cnt
        sq = jnp.sum(jnp.square(d.astype(jnp.float32)), axis=red) / cnt
        var = jnp.maximum(sq - jnp.square(mean_d), 0.0)
        mean = mean_d + probe.astype(jnp.float32)
    else:
        mean, var = moving_mean, moving_var
    g = jnp.ones_like(gamma) if attrs["fix_gamma"] else gamma
    scale = (g.astype(jnp.float32)
             * jax.lax.rsqrt(var + attrs["eps"])).astype(x.dtype)
    shift = (beta.astype(jnp.float32)
             - mean * scale.astype(jnp.float32)).astype(x.dtype)
    out = x * scale.reshape(bshape) + shift.reshape(bshape)
    if act_type == "relu":  # peephole fallback when Pallas is ineligible
        out = jnp.maximum(out, 0)
    outs = [out, mean, var] if attrs["output_mean_var"] else [out]
    if use_batch:
        m = attrs["momentum"]
        new_mean = moving_mean * m + jax.lax.stop_gradient(mean) * (1 - m)
        new_var = moving_var * m + jax.lax.stop_gradient(var) * (1 - m)
        return outs, [new_mean, new_var]
    return outs, None


register("BatchNorm", _batch_norm,
         arguments=("data", "gamma", "beta"),
         aux_states=("moving_mean", "moving_var"),
         outputs=lambda a: ["output", "mean", "var"] if a["output_mean_var"]
         else ["output"],
         params={"eps": (pfloat, 1e-3), "momentum": (pfloat, 0.9),
                 "fix_gamma": (pbool, True), "use_global_stats": (pbool, False),
                 "output_mean_var": (pbool, False)},
         aliases=("CuDNNBatchNorm",), hint="batchnorm")


def _instance_norm(attrs, inputs, aux, is_train, rng):
    x, gamma, beta = inputs
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    out = (x - mean) * jax.lax.rsqrt(var + attrs["eps"])
    return [out * gamma.reshape(bshape) + beta.reshape(bshape)]


register("InstanceNorm", _instance_norm, arguments=("data", "gamma", "beta"),
         params={"eps": (pfloat, 1e-3)}, hint="instancenorm")


def _l2_normalization(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    mode, eps = attrs["mode"], attrs["eps"]
    if mode == "instance":
        red, keep = tuple(range(1, x.ndim)), True
    elif mode == "channel":
        red, keep = (1,), True
    elif mode == "spatial":
        red, keep = tuple(range(2, x.ndim)), True
    else:
        raise MXNetError("L2Normalization: bad mode %r" % mode)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=keep) + eps)
    return [x / norm]


register("L2Normalization", _l2_normalization,
         params={"eps": (pfloat, 1e-10), "mode": (pstr, "instance")},
         hint="l2normalization")


def _lrn(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    n = attrs["nsize"]
    sq = jnp.square(x)
    half = n // 2
    win = (1, n) + (1,) * (x.ndim - 2)
    pad = ((0, 0), (half, n - 1 - half)) + ((0, 0),) * (x.ndim - 2)
    ssum = jax.lax.reduce_window(sq, 0.0, jax.lax.add,
                                 win, (1,) * x.ndim, pad)
    scale = attrs["knorm"] + (attrs["alpha"] / n) * ssum
    return [x * jnp.power(scale, -attrs["beta"])]


register("LRN", _lrn,
         params={"alpha": (pfloat, 1e-4), "beta": (pfloat, 0.75),
                 "knorm": (pfloat, 2.0), "nsize": (pint, REQUIRED)}, hint="lrn")


# ---------------------------------------------------------------------------
# Dropout — needs rng; identity at inference (reference ``dropout-inl.h``)
# ---------------------------------------------------------------------------
def _dropout(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    p = attrs["p"]
    if not is_train or p <= 0.0:
        return [x]
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return [jnp.where(mask, x / keep, jnp.zeros_like(x))]


register("Dropout", _dropout, params={"p": (pfloat, 0.5)}, needs_rng=True,
         hint="dropout")


# ---------------------------------------------------------------------------
# Concat / SliceChannel / Pad / UpSampling / Sequence ops
# ---------------------------------------------------------------------------
def _concat(attrs, inputs, aux, is_train, rng):
    return [jnp.concatenate(inputs, axis=attrs["dim"])]


register("Concat", _concat,
         arguments=lambda a: ["arg%d" % i for i in range(a["num_args"])],
         params={"num_args": (pint, REQUIRED), "dim": (pint, 1)},
         key_var_num_args="num_args", aliases=("concat",), hint="concat")


def _slice_channel(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    parts = jnp.split(x, attrs["num_outputs"], axis=attrs["axis"])
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=attrs["axis"]) for p in parts]
    return list(parts)


register("SliceChannel", _slice_channel,
         outputs=lambda a: ["output%d" % i for i in range(a["num_outputs"])],
         params={"num_outputs": (pint, REQUIRED), "axis": (pint, 1),
                 "squeeze_axis": (pbool, False)},
         aliases=("split",), hint="slicechannel")


def _pad(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    pw = attrs["pad_width"]
    pads = [(pw[2 * i], pw[2 * i + 1]) for i in range(x.ndim)]
    mode = attrs["mode"]
    if mode == "constant":
        return [jnp.pad(x, pads, constant_values=attrs["constant_value"])]
    return [jnp.pad(x, pads, mode={"edge": "edge", "reflect": "reflect"}[mode])]


register("Pad", _pad,
         params={"mode": (pstr, "constant"), "pad_width": (ptuple, REQUIRED),
                 "constant_value": (pfloat, 0.0)},
         aliases=("pad",), hint="pad")


def _upsampling(attrs, inputs, aux, is_train, rng):
    s = attrs["scale"]
    if attrs["sample_type"] == "nearest":
        outs = []
        for x in inputs:
            r = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
            outs.append(r)
        if len(outs) == 1:
            return [outs[0]]
        return [jnp.concatenate(outs, axis=1)]
    # bilinear: reference uses an internal Deconvolution with a learnable
    # kernel (data, weight); XLA-native resize is used for the interpolation.
    x = inputs[0]
    new = x.shape[:2] + (x.shape[2] * s, x.shape[3] * s)
    return [jax.image.resize(x, new, method="bilinear")]


register("UpSampling", _upsampling,
         arguments=lambda a: (["arg%d" % i for i in range(a["num_args"])]
                              if a["sample_type"] == "nearest"
                              else ["data", "weight"]),
         params={"scale": (pint, REQUIRED), "num_filter": (pint, 0),
                 "sample_type": (pstr, REQUIRED), "multi_input_mode": (pstr, "concat"),
                 "num_args": (pint, 1), "workspace": (pint, 512)},
         key_var_num_args="num_args", hint="upsampling")


# Sequence ops (time-major (T, N, ...), reference ``sequence_*-inl.h``)
def _seq_args(a):
    return ["data", "sequence_length"] if a["use_sequence_length"] else ["data"]


def _sequence_last(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    if attrs["use_sequence_length"]:
        idx = (inputs[1].astype(jnp.int32) - 1).clip(0, x.shape[0] - 1)
        return [jnp.take_along_axis(
            x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0)[0]]
    return [x[-1]]


register("SequenceLast", _sequence_last, arguments=_seq_args,
         params={"use_sequence_length": (pbool, False)}, hint="sequencelast")


def _seq_mask_array(x, seqlen):
    t = x.shape[0]
    steps = jnp.arange(t).reshape((t, 1))
    return steps < seqlen.astype(jnp.int32).reshape((1, -1))


def _sequence_mask(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    if not attrs["use_sequence_length"]:
        return [x]
    mask = _seq_mask_array(x, inputs[1]).reshape(
        x.shape[:2] + (1,) * (x.ndim - 2))
    return [jnp.where(mask, x, jnp.asarray(attrs["value"], x.dtype))]


register("SequenceMask", _sequence_mask, arguments=_seq_args,
         params={"use_sequence_length": (pbool, False), "value": (pfloat, 0.0)},
         hint="sequencemask")


def _sequence_reverse(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    if not attrs["use_sequence_length"]:
        return [jnp.flip(x, axis=0)]
    t = x.shape[0]
    seqlen = inputs[1].astype(jnp.int32).reshape((1, -1))
    steps = jnp.arange(t).reshape((t, 1))
    src = jnp.where(steps < seqlen, seqlen - 1 - steps, steps)
    src = src.reshape(x.shape[:2] + (1,) * (x.ndim - 2))
    src = jnp.broadcast_to(src, x.shape)
    return [jnp.take_along_axis(x, src, axis=0)]


register("SequenceReverse", _sequence_reverse, arguments=_seq_args,
         params={"use_sequence_length": (pbool, False)}, hint="sequencereverse")
