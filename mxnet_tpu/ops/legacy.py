"""Legacy NDArray-function registry ops + plugin-analog ops.

Reference: the ``MXNET_REGISTER_NDARRAY_FUN`` census
(``src/ndarray/ndarray.cc:748-867``: ``_set_value``, ``_onehot_encode``,
``_copyto``, ``_broadcast``, ``_imdecode``; ``choose_element_0index`` and
``fill_element_0index`` live in ``mxnet_tpu.ops.matrix``/``indexing``),
the NNVM slice-assign pair (``src/operator/tensor/matrix_op.cc``:
``_slice_assign``/``_crop_assign_scalar``), ``Convolution_v1``
(``src/operator/convolution_v1.cc`` — same math as Convolution), and the
WarpCTC plugin (``plugin/warpctc/warpctc-inl.h``) whose TPU-native analog
is a CTC loss lowered through XLA (core DP from ``optax.ctc_loss``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .helpers import simple
from .registry import (REQUIRED, pbool, pfloat, pint, pstr, ptuple, register,
                       _ALIASES)


def _region(begin, end, shape):
    return tuple(slice(b, e if e != 0 or b != 0 else None)
                 for b, e in zip(begin, end)) + \
        tuple(slice(None) for _ in range(len(shape) - len(begin)))


def _slice_assign(lhs, rhs, begin, end):
    return lhs.at[_region(begin, end, lhs.shape)].set(rhs)


simple("_slice_assign", _slice_assign, arguments=("lhs", "rhs"),
       params={"begin": (ptuple, REQUIRED), "end": (ptuple, REQUIRED)},
       aliases=("_crop_assign",))


def _crop_assign_scalar(data, begin, end, scalar):
    reg = _region(begin, end, data.shape)
    return data.at[reg].set(jnp.asarray(scalar, data.dtype))


simple("_crop_assign_scalar", _crop_assign_scalar,
       params={"begin": (ptuple, REQUIRED), "end": (ptuple, REQUIRED),
               "scalar": (pfloat, 0.0)},
       aliases=("_slice_assign_scalar",))

# _set_value: fill the (existing) array with a scalar (ndarray.cc:748)
simple("_set_value", lambda data, src: jnp.full_like(data, src),
       params={"src": (pfloat, REQUIRED)})


def _onehot_encode(indices, out):
    """(indices, out) -> one-hot written over ``out`` (ndarray.cc:767)."""
    depth = out.shape[-1]
    return jax.nn.one_hot(indices.astype(jnp.int32), depth,
                          dtype=out.dtype)


simple("_onehot_encode", _onehot_encode, arguments=("indices", "out"),
       aliases=("onehot_encode",))

# _broadcast: explicit broadcast of 1-dims up to a full shape (ndarray.cc:818)
simple("_broadcast", lambda data, shape: jnp.broadcast_to(data, shape),
       params={"shape": (ptuple, REQUIRED)})

# _copyto / Convolution_v1 are pure aliases of existing ops
_ALIASES["_copyto"] = "_copy"
_ALIASES["Convolution_v1"] = "Convolution"

# _CrossDeviceCopy: the PlaceDevice pass's placeholder node
# (src/operator/cross_device_copy.cc — carries no compute; the executor
# performs the copy).  Under XLA the "copy" is a sharding/placement decision
# made by the compiler, so the node lowers to identity; the group2ctx
# machinery in executor.py owns actual placement.
simple("_CrossDeviceCopy", lambda data: data)


# _imdecode (``src/ndarray/ndarray.cc:832``) is a host-side decode and never
# appears in a graph; it lives as an NDArray function in mxnet_tpu.ndarray.


# ---------------------------------------------------------------------------
# CTC loss — the WarpCTC plugin analog (plugin/warpctc/warpctc-inl.h)
# ---------------------------------------------------------------------------

def _ctc_loss(attrs, inputs, aux, is_train, rng):
    import optax

    data, label = inputs[0], inputs[1]
    # reference layout: data (seq_len, batch, alphabet), label (batch, L)
    logits = jnp.transpose(data, (1, 0, 2)).astype(jnp.float32)
    labels = label.astype(jnp.int32)
    if labels.ndim == 1:
        labels = labels[:, None]
    blank = 0
    if attrs["blank_label"] == "last":
        blank = data.shape[-1] - 1
        pad_mask = (labels == -1) | (labels >= blank)
    else:
        # blank_label='first': class 0 is blank, 0 also pads labels
        pad_mask = labels <= 0
    logit_pad = jnp.zeros(logits.shape[:2], jnp.float32)
    loss = optax.ctc_loss(logits, logit_pad, labels,
                          pad_mask.astype(jnp.float32), blank_id=blank)
    return [loss.astype(data.dtype)]


register("CTCLoss", _ctc_loss, arguments=("data", "label"),
         params={"use_data_lengths": (pbool, False),
                 "use_label_lengths": (pbool, False),
                 "blank_label": (pstr, "first")},
         aliases=("ctc_loss", "_contrib_CTCLoss", "WarpCTC"))
