"""graftrace — distributed request/step tracing.

The stack is a distributed system — replica pools migrate generation
sessions across engines (PR 12), elastic KVStore jobs reshard across
worker processes (PR 11), supervised fleets restart members under a
harness (PR 15/16) — but until now no identifier survived a hop: a
generation that failed over mid-decode, or a reshard cycle spanning
four workers, could not be reconstructed after the fact.  This module
mints a ``trace_id``/``span_id`` at every entry point (HTTP request,
batcher submit, decode session, ``fit`` batch, checkpoint write,
elastic reshard) and carries it through routing → dispatch → failover
→ resume, and over the KVStore wire (an optional ``trace`` field on
push/pull/barrier/reshard verbs) so worker↔coordinator spans stitch
into one tree.

Span model (a deliberately small slice of the OpenTelemetry shape):

* a **trace** is one request/step's causal tree, identified by a
  16-hex ``trace_id``;
* a **span** is one timed operation inside it — 8-hex ``span_id``,
  ``parent_id`` link, wall-clock ``t0``/``t1``, measured ``dur_s``,
  free-form ``attrs``, and a typed ``status``: ``ok`` / ``shed`` /
  ``migrated`` / ``retry`` / ``error`` (``in_flight`` for live spans
  in a :func:`tree` read);
* parenting is implicit on one thread (a thread-local span stack) and
  explicit across threads/processes (``parent=`` a :class:`Span`, or
  ``trace_id=``/``parent_id=`` from a wire context).

Finished spans land in a bounded ring (``MXNET_TRACE_RING``, default
4096) that the flight recorder dumps as ndjson
(``spans-<pid>-<seq>-<reason>.ndjson``) and ``GET /trace/<id>`` on the
serving frontend assembles — live spans included — via :func:`tree`.
When the chrome-trace profiler is running, every ended span is also a
``profiler.record`` event on the same timeline as phase/dispatch
spans.

Cost model (the PR 2 discipline): tracing is OFF by default and
:func:`start_span` checks one module bool first, returning the shared
falsy :data:`NULL_SPAN` — a disabled entry point pays one call and one
branch, no clock read, no allocation.  Enable with ``MXNET_TRACE=1``
(or :func:`enable`); tests/test_tracing.py pins the disabled per-batch
overhead.

See docs/observability.md "Distributed tracing & fleet aggregation".
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import profiler as _profiler

__all__ = ["enabled", "enable", "disable", "start_span", "current",
           "ctx", "tree", "spans_recent", "reset", "Span", "NULL_SPAN",
           "STATUSES"]

#: the typed span statuses (``in_flight`` is synthesized for live
#: spans in :func:`tree` reads, never stored)
STATUSES = ("ok", "shed", "migrated", "retry", "error")


def _ring_size():
    try:
        return max(64, int(os.environ.get("MXNET_TRACE_RING", "") or 4096))
    except ValueError:
        return 4096


_lock = threading.Lock()
_ring = deque(maxlen=_ring_size())   # finished span dicts, oldest first
_live = {}                           # span_id -> Span (in flight)
_tls = threading.local()

_enabled = os.environ.get("MXNET_TRACE", "0") not in ("0", "", "false")


def enabled():
    """True when spans record (``MXNET_TRACE=1`` or :func:`enable`);
    the one check every entry point makes."""
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def _new_id(nbytes):
    return os.urandom(nbytes).hex()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _NullSpan:
    """The falsy no-op span a disabled :func:`start_span` returns:
    every method is a pass, so instrumented code needs no enablement
    branches of its own."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None

    def __bool__(self):
        return False

    def annotate(self, **attrs):
        pass

    def end(self, status="ok", **attrs):
        pass

    def ctx(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


#: the shared disabled-mode span (one allocation per process)
NULL_SPAN = _NullSpan()


class Span:
    """One live span.  Create via :func:`start_span`; finish EXACTLY
    once via :meth:`end` (idempotent — a second call is ignored, so a
    failover path and a late resolve cannot double-record)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0",
                 "status", "attrs", "_pc0", "_stacked", "_ended")

    def __init__(self, name, trace_id, parent_id):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id(4)
        self.parent_id = parent_id
        self.t0 = time.time()
        self.status = None
        self.attrs = {}
        self._pc0 = time.perf_counter()
        self._stacked = False
        self._ended = False

    def __bool__(self):
        return True

    def annotate(self, **attrs):
        """Attach attributes to a live span (last write per key wins)."""
        self.attrs.update(attrs)

    def ctx(self):
        """The wire context: ``{"trace_id", "span_id"}`` — what a
        KVStore message or a cross-process hand-off carries so the
        remote side can parent its span here."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def _snapshot(self, live=False):
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "t0": round(self.t0, 6),
                "status": "in_flight" if live else self.status,
                "attrs": dict(self.attrs)}

    def end(self, status="ok", **attrs):
        """Finish the span with a typed ``status``; moves it from the
        live set into the bounded finished ring (and onto the
        chrome-trace timeline when the profiler runs)."""
        prof = _profiler.running()
        end_us = _profiler.now_us() if prof else 0.0
        dur = time.perf_counter() - self._pc0
        with _lock:
            if self._ended:
                return
            self._ended = True
            self.status = status
            if attrs:
                self.attrs.update(attrs)
            _live.pop(self.span_id, None)
            rec = {"trace_id": self.trace_id, "span_id": self.span_id,
                   "parent_id": self.parent_id, "name": self.name,
                   "t0": round(self.t0, 6),
                   "t1": round(self.t0 + dur, 6),
                   "dur_s": round(dur, 6), "status": status,
                   "attrs": dict(self.attrs)}
            _ring.append(rec)
        if self._stacked:
            st = getattr(_tls, "stack", None)
            # only pop when ending on the opening thread with this
            # span on top — a cross-thread end (failover resolve) must
            # not corrupt another thread's stack
            if st and st[-1] is self:
                st.pop()
        if prof:
            _profiler.record("trace:%s" % self.name, "trace",
                             end_us - dur * 1e6, end_us)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.end("error", error=str(exc))
        else:
            self.end("ok")
        return False


def start_span(name, parent=None, trace_id=None, parent_id=None,
               stack=True, **attrs):
    """Open a span.

    Parent resolution, in order: an explicit ``parent`` :class:`Span`
    (the cross-thread hand-off — an engine loop parents on the
    session's root span), an explicit wire context
    (``trace_id``/``parent_id`` from a KVStore message), else the
    calling thread's current span; with none of those this span ROOTS
    a fresh trace.  ``stack=False`` opts out of thread-local parenting
    for spans that outlive their opening call (a session's root span
    must not become the implicit parent of unrelated work on the
    submitting thread).  Returns :data:`NULL_SPAN` when disabled."""
    if not _enabled:
        return NULL_SPAN
    if parent is not None and parent:
        tid, pid = parent.trace_id, parent.span_id
    elif trace_id is not None:
        tid, pid = trace_id, parent_id
    else:
        cur = _stack()
        top = cur[-1] if cur else None
        if top is not None:
            tid, pid = top.trace_id, top.span_id
        else:
            tid, pid = _new_id(8), None
    sp = Span(name, tid, pid)
    if attrs:
        sp.attrs.update(attrs)
    with _lock:
        # bound the live set too: a span that is never ended (a bug,
        # or an abandoned session) must not leak forever — evict the
        # oldest as force-ended
        if len(_live) >= max(1024, _ring.maxlen):
            oldest = next(iter(_live.values()))
            _live.pop(oldest.span_id, None)
            oldest._ended = True
            _ring.append(oldest._snapshot(live=False) | {
                "t1": None, "dur_s": None, "status": "error",
                "attrs": dict(oldest.attrs, dropped="live-ring-full")})
        _live[sp.span_id] = sp
    if stack:
        sp._stacked = True
        _stack().append(sp)
    return sp


def current():
    """The calling thread's innermost live span, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def ctx():
    """The calling thread's current wire context (``{"trace_id",
    "span_id"}``), or None — what :meth:`KVStore._with_trace` stamps
    onto outgoing verbs.  One attr read when disabled/absent."""
    st = getattr(_tls, "stack", None)
    return st[-1].ctx() if st else None


def _trace_spans(trace_id):
    """Every recorded span of one trace: finished (from the ring) plus
    live (synthesized ``in_flight``), lock held by caller."""
    out = [dict(r) for r in _ring if r["trace_id"] == trace_id]
    out.extend(sp._snapshot(live=True) for sp in _live.values()
               if sp.trace_id == trace_id)
    return out


def tree(trace_id):
    """Assemble one trace into a nested tree:

    ``{"trace_id", "n_spans", "root": {span..., "children": [...]},
    "extra_roots": [...], "orphans": [...], "complete": bool}``

    — ``orphans`` are spans whose parent is not in the trace (the
    chaos acceptance asserts this stays empty across a replica kill),
    ``extra_roots`` any parentless span beyond the first, and
    ``complete`` is True when a root exists, nothing is orphaned, and
    no span is still in flight.  Returns None for an unknown id."""
    with _lock:
        spans = _trace_spans(trace_id)
    if not spans:
        return None
    spans.sort(key=lambda s: s["t0"])
    ids = {s["span_id"] for s in spans}
    children = {}
    roots, orphans = [], []
    for s in spans:
        pid = s["parent_id"]
        if pid is None:
            roots.append(s)
        elif pid in ids:
            children.setdefault(pid, []).append(s)
        else:
            orphans.append(s)

    def nest(s):
        return dict(s, children=[nest(c)
                                 for c in children.get(s["span_id"], [])])

    in_flight = any(s["status"] == "in_flight" for s in spans)
    return {"trace_id": trace_id, "n_spans": len(spans),
            "root": nest(roots[0]) if roots else None,
            "extra_roots": [nest(r) for r in roots[1:]],
            "orphans": [dict(s) for s in orphans],
            "complete": bool(roots) and not roots[1:] and not orphans
            and not in_flight}


def spans_recent(n=1000):
    """The newest ``n`` FINISHED spans (copies, oldest first) — what
    the flight recorder dumps as its ndjson span ring."""
    with _lock:
        return [dict(r) for r in list(_ring)[-int(n):]]


def reset():
    """Clear the finished ring and the live set (tests; enablement and
    other threads' stacks are unchanged)."""
    global _ring
    with _lock:
        _ring = deque(maxlen=_ring_size())
        _live.clear()
    st = getattr(_tls, "stack", None)
    if st:
        del st[:]
