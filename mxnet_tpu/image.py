"""Host-side image decode / augment / iterate pipeline.

Reference: ``python/mxnet/image.py`` (python aug pipeline) and the C++
``ImageRecordIter`` stack (``src/io/iter_image_recordio.cc``,
``src/io/image_aug_default.cc`` — crop/resize/mirror/HSL jitter under
``MXNET_REGISTER_IMAGE_AUGMENTER``).

TPU design: decode + augment stay on host CPU (numpy/OpenCV) exactly like
the reference — the chip never sees JPEGs — and the batch is shipped once
per step; ``io.PrefetchingIter`` provides the background-thread double
buffering of the reference's ``PrefetcherIter`` (``iter_prefetcher.h:49``).
Images flow as HWC uint8 RGB between augmenters, NCHW float32 out of the
iterator (the ``Module`` input layout).
"""

from __future__ import annotations

import logging
import os
import random as pyrandom

import numpy as np

from . import ndarray
from . import recordio
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["imdecode", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "HorizontalFlipAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "CenterCropAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug", "CastAug",
           "CreateAugmenter", "ImageIter"]


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError as e:  # pragma: no cover
        raise MXNetError("OpenCV is required for image ops: %s" % e)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode encoded image bytes -> HWC uint8 (RGB by default)."""
    cv2 = _cv2()
    img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
    if img is None:
        raise MXNetError("cannot decode image")
    if to_rgb and img.ndim == 3 and img.shape[2] == 3:
        # SIMD channel swap; a reversed view + ascontiguousarray costs a
        # strided copy per image
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return img


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    return cv2.resize(src, (w, h), interpolation=interp)


def resize_short(src, size, interp=1):
    """Resize so the shorter edge equals ``size`` (aspect preserved)."""
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype(np.float32) - mean
    if std is not None:
        src = src / std
    return src


# ---------------------------------------------------------------------------
# augmenters: callables image -> image, composed in a list (the
# MXNET_REGISTER_IMAGE_AUGMENTER analog is plain python composition)
# ---------------------------------------------------------------------------

class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return src[:, ::-1]  # view; batch staging copies it anyway
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return (src.astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, contrast):
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        src = src.astype(np.float32)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray.mean() * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, saturation):
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        src = src.astype(np.float32)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness, contrast, saturation):
        self.augs = []
        if brightness > 0:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation > 0:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        augs = list(self.augs)
        pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


class LightingAug(Augmenter):
    """PCA lighting noise (AlexNet-style), reference image_aug_default."""

    def __init__(self, alphastd, eigval, eigvec):
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(np.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return src.astype(np.float32) + rgb


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class CastAug(Augmenter):
    def __call__(self, src):
        return src.astype(np.float32)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=1):
    """Reference ``python/mxnet/image.py`` CreateAugmenter: standard
    training/eval augmentation chain for (C, H, W) ``data_shape``."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


def _native_aug_plan(aug_list, data_shape):
    """Match the fast-path aug chain [ResizeAug?, RandomCrop|CenterCrop,
    HorizontalFlipAug?] against the native batch decoder's capabilities
    (src/imgdecode.cc).  Returns (resize_shorter, random_crop, flip_p) or
    None when the chain needs the Python per-image path."""
    if data_shape[0] != 3:
        return None
    resize = 0
    augs = list(aug_list)
    if augs and isinstance(augs[0], ResizeAug):
        if augs[0].interp != 1:  # native resize is bilinear only
            return None
        resize = augs.pop(0).size
    if not augs or not isinstance(augs[0], (RandomCropAug, CenterCropAug)):
        return None
    crop = augs.pop(0)
    if tuple(crop.size) != (data_shape[2], data_shape[1]):
        return None
    if crop.interp != 1:
        return None
    flip_p = 0.0
    if augs and isinstance(augs[0], HorizontalFlipAug):
        flip_p = augs.pop(0).p
    if augs:
        return None
    return resize, isinstance(crop, RandomCropAug), flip_p


class ImageIter(DataIter):
    """Image iterator over a RecordIO shard or an image list.

    Reference: python ``ImageIter`` (``python/mxnet/image.py``) and the C++
    ``ImageRecordIter`` (``src/io/iter_image_recordio.cc``), including its
    distributed sharding (``part_index``/``num_parts``) and shuffle.
    Produces NCHW float32 batches; wrap in ``io.PrefetchingIter`` for
    background decode (the C++ prefetcher analog).
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", last_batch_handle="pad",
                 preprocess_threads=1, post_batch=None, native_norm=None,
                 **kwargs):
        super().__init__(batch_size)
        # post_batch(hwc_batch, label, valid) -> (data NDArray, label
        # NDArray): batch-level cast/normalize/transpose (host-vectorized
        # or on-device) replacing the per-image CastAug chain; augmenters
        # must then keep images uint8 HWC (geometric ops only)
        self._post_batch = post_batch
        # parallel decode/augment on the native engine's worker pool
        # (the C++ ImageRecordIter's preprocess_threads,
        # iter_image_recordio.cc) — cv2 releases the GIL during decode
        # created lazily on the first batch that actually needs it — when
        # the native batch decoder engages, the Python-side worker pool
        # would only sit idle
        self._engine = None
        self._engine_workers = preprocess_threads
        assert path_imgrec or path_imglist or imglist is not None, \
            "one of path_imgrec / path_imglist / imglist is required"
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.data_name, self.label_name = data_name, label_name
        self.imgrec = None
        self.imglist = None
        self.path_root = path_root

        if path_imgrec:
            idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + ".idx"
            # MXIndexedRecordIO rebuilds a positional index via the native
            # scanner when the .idx file is missing
            self.imgrec = recordio.MXIndexedRecordIO(
                idx_path, path_imgrec, "r")
            if self.imgrec.keys:
                self.seq = list(self.imgrec.keys)
            else:
                if shuffle or num_parts > 1:
                    raise MXNetError(
                        "shuffle/num_parts>1 require an index (missing %s "
                        "and the native scanner is unavailable); build one "
                        "with tools/im2rec.py" % idx_path)
                # no index at all: sequential-only access
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist:
            self.imglist = {}
            seq = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = np.array(parts[1:-1], dtype=np.float32)
                    key = int(parts[0])
                    self.imglist[key] = (label, parts[-1])
                    seq.append(key)
            self.seq = seq
        else:
            self.imglist = {}
            seq = []
            for i, (label, fname) in enumerate(imglist):
                self.imglist[i] = (np.array(label, ndmin=1,
                                            dtype=np.float32), fname)
                seq.append(i)
            self.seq = seq

        if self.seq is not None and num_parts > 1:
            # distributed shard (iter_mnist.cc-style part_index/num_parts)
            n = len(self.seq)
            per = n // num_parts
            self.seq = self.seq[part_index * per:
                                (part_index + 1) * per if part_index
                                < num_parts - 1 else n]
        self.aug_list = (CreateAugmenter(data_shape, **{
            k: v for k, v in kwargs.items()
            if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                     "mean", "std", "brightness", "contrast", "saturation",
                     "pca_noise", "inter_method")})
            if aug_list is None else aug_list)
        # native batch decode (src/imgdecode.cc): eligible when the fast
        # path is active (uint8 staging via post_batch, or fused f32
        # output via native_norm — the multi-process workers use the
        # latter alone, their post step IS the norm) and the aug chain
        # is purely geometric; the library loads lazily on first next()
        self._native_plan = _native_aug_plan(self.aug_list, data_shape) \
            if (post_batch is not None or native_norm is not None) \
            else None
        # (mean, std, scale) for the native fused f32-NCHW output; only
        # meaningful for host batches (device conversion ships uint8)
        self._native_norm = native_norm
        # optional caller-provided output buffers for the NEXT batch:
        # (f32 NCHW data_buf, f32 label_buf).  The native f32 path
        # decodes straight into them (the multi-process decode workers
        # point this at a shared-memory slot, making the IPC handoff
        # zero-copy); consumed once, then reset to None.
        self.batch_out = None
        self._preprocess_threads = max(1, int(preprocess_threads))
        assert last_batch_handle in ("pad", "discard", "roll_over"), \
            last_batch_handle
        self.last_batch_handle = last_batch_handle
        self._overflow = 0
        self.cursor = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape, np.float32)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self.label_name, shape, np.float32)]

    def reset(self):
        # roll_over (reference round_batch=1, iter_batchloader.h:36): the
        # wrapped final batch already consumed the FIRST ov samples of
        # the next epoch's order (_wrap_start reshuffled), so keep that
        # permutation and skip them — every sample is seen once per cycle
        self._exhausted = False
        ov = getattr(self, "_overflow", 0)
        self._overflow = 0
        if ov:
            if self.seq is not None:
                self.cursor = ov
            else:
                self.imgrec.reset()
                for _ in range(ov):
                    self.imgrec.read()
                self.cursor = ov
            return
        if self.seq is not None and self.shuffle:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cursor = 0

    def _maybe_engine(self):
        """Python-side decode worker pool, created on first use (the
        reference's preprocess_threads, iter_image_recordio.cc — cv2
        releases the GIL so threads overlap)."""
        if self._engine is None and self._engine_workers > 1:
            self._engine_workers = 1  # one attempt
            try:
                from .native import Engine

                self._engine = Engine(num_workers=self._preprocess_threads)
            except RuntimeError:
                logging.warning("native engine unavailable; "
                                "decoding on one thread")
        return self._engine

    def _wrap_start(self):
        """Start the NEXT epoch's read order mid-batch (roll_over fill):
        the wrapped samples are the first of the new epoch."""
        if self.seq is not None and self.shuffle:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cursor = 0

    def _gather_batch_raws(self):
        """Collect up to batch_size (bytes-or-img, label) items applying
        the last-batch policy: 'pad' returns a short list (caller pads),
        'discard' drops the partial batch, 'roll_over' wraps to the start
        and notes the overflow for the next reset()."""
        if self._exhausted:
            raise StopIteration
        raws = []
        while len(raws) < self.batch_size:
            item = self._read_raw()
            if item is None:
                if not raws:
                    raise StopIteration
                if self.last_batch_handle == "discard":
                    raise StopIteration
                if self.last_batch_handle == "roll_over":
                    # wrap to the start to complete the epoch's FINAL
                    # batch; the epoch ends after it
                    self._wrap_start()
                    self._overflow = self.batch_size - len(raws)
                    self._exhausted = True
                    continue
                break  # pad
            raws.append(item)
        return raws

    # -- iterator-state protocol (docs/resilience.md "exact resume") ------
    def state_dict(self):
        """Mid-epoch position: the cursor plus — because ``shuffle``
        permutes ``seq`` in place per epoch — the current read ORDER,
        and the raw record position for index-less sequential shards.
        Restoring on an equivalently-constructed iterator replays the
        exact remaining sample sequence of this epoch."""
        state = {"type": "ImageIter", "cursor": int(self.cursor),
                 "overflow": int(getattr(self, "_overflow", 0)),
                 "exhausted": bool(getattr(self, "_exhausted", False)),
                 "seq": list(self.seq) if self.seq is not None else None}
        if self.seq is None and self.imgrec is not None:
            state["record"] = self.imgrec.state_dict()
        return state

    def load_state_dict(self, state):
        if state.get("type", "ImageIter") != "ImageIter":
            raise MXNetError("iterator state of type %r cannot restore "
                             "onto ImageIter" % (state.get("type"),))
        self.cursor = int(state["cursor"])
        self._overflow = int(state.get("overflow", 0))
        self._exhausted = bool(state.get("exhausted", False))
        if state.get("seq") is not None:
            if self.seq is None:
                raise MXNetError("ImageIter state carries a sample order "
                                 "but this iterator has no index")
            self.seq = list(state["seq"])
        elif self.imgrec is not None and state.get("record") is not None:
            self.imgrec.load_state_dict(state["record"])

    def _read_raw(self):
        """Fetch one (encoded bytes, label) — file IO only, main thread."""
        if self.imgrec is not None:
            if self.seq is not None:
                if self.cursor >= len(self.seq):
                    return None
                rec = self.imgrec.read_idx(self.seq[self.cursor])
            else:
                rec = self.imgrec.read()
                if rec is None:
                    return None
            self.cursor += 1
            header, img_bytes = recordio.unpack(rec)
            return img_bytes, header.label
        if self.cursor >= len(self.seq):
            return None
        label, fname = self.imglist[self.seq[self.cursor]]
        self.cursor += 1
        path = os.path.join(self.path_root, fname) if self.path_root \
            else fname
        with open(path, "rb") as f:
            return f.read(), label

    def _decode_augment(self, img_bytes):
        img = imdecode(img_bytes)
        for aug in self.aug_list:
            img = aug(img)
        return img

    def next(self):
        c, h, w = self.data_shape
        post = self._post_batch
        # fast path stages uint8 HWC (geometric augs preserve dtype) and
        # converts once per batch; classic path converts per image (the
        # aug chain may produce float, e.g. CastAug/ColorNormalizeAug)
        hwc = np.empty((self.batch_size, h, w, c), np.uint8) \
            if post is not None else None
        data = None if post is not None \
            else np.empty((self.batch_size, c, h, w), np.float32)
        if self.label_width == 1:
            label = np.zeros((self.batch_size,), np.float32)
        else:
            label = np.zeros((self.batch_size, self.label_width), np.float32)

        def fill(i, img, lbl):
            if img.ndim == 2:
                img = img[:, :, None]
            if post is not None:
                hwc[i] = img
            else:
                data[i] = np.asarray(img, np.float32).transpose(2, 0, 1)
            lbl = np.asarray(lbl).reshape(-1)
            if self.label_width == 1:
                label[i] = lbl[0]
            else:
                label[i] = lbl[:self.label_width]

        i = 0
        native_lib = None
        if self._native_plan is not None and \
                (post is not None or self._native_norm is not None):
            from .native import get_imgdecode_lib

            native_lib = get_imgdecode_lib()
        if native_lib is not None:
            # one C call decodes+augments the whole batch (reference: the
            # C++ parser threads of iter_image_recordio.cc:458); with
            # native_norm set the call also fuses cast+normalize+
            # transpose and fills f32 NCHW directly — the host post pass
            # costs as much as the decode, so fusing it in doubles the
            # host pipeline rate
            from .native import imgdecode_batch

            raws = self._gather_batch_raws()
            n = len(raws)
            resize, rand_c, flip_p = self._native_plan
            fx = [(pyrandom.random() if rand_c else -1.0)
                  for _ in range(n)]
            fy = [(pyrandom.random() if rand_c else -1.0)
                  for _ in range(n)]
            mir = [1 if (flip_p and pyrandom.random() < flip_p) else 0
                   for _ in range(n)]
            f32_mode = self._native_norm is not None
            if f32_mode:
                if self.batch_out is not None:
                    nchw, label_buf = self.batch_out
                    self.batch_out = None
                    label = label_buf.reshape(label.shape)
                else:
                    nchw = np.empty((self.batch_size, c, h, w),
                                    np.float32)
                out_arr, norm = nchw, self._native_norm
            else:
                out_arr, norm = hwc, None
            bad = imgdecode_batch(
                native_lib, [b for b, _ in raws], out_arr, resize,
                fx, fy, mir, h, w, norm=norm,
                nthreads=self._preprocess_threads)
            if bad:
                raise MXNetError(
                    "%d image(s) failed to decode in this batch" % bad)
            for j, (_b, lbl) in enumerate(raws):
                lbl = np.asarray(lbl).reshape(-1)
                if self.label_width == 1:
                    label[j] = lbl[0]
                else:
                    label[j] = lbl[:self.label_width]
            if f32_mode:
                pad = self.batch_size - n
                for j in range(n, self.batch_size):
                    nchw[j] = nchw[n - 1]
                    label[j] = label[n - 1]
                # zero-copy host wrap: nchw/label are freshly allocated
                # per batch, so the executor can device_put them straight
                # from this buffer (saves the 77 MB/batch staging memcpy)
                return DataBatch(
                    data=[ndarray.from_host(nchw)],
                    label=[ndarray.from_host(label)], pad=pad,
                    provide_data=self.provide_data,
                    provide_label=self.provide_label)
            i = n
        elif self._maybe_engine() is not None:
            # raw reads on this thread, decode+augment fanned out to the
            # native engine workers; slots are disjoint → no mutable deps
            raws = self._gather_batch_raws()
            errors = []
            for j, (img_bytes, lbl) in enumerate(raws):
                def work(j=j, img_bytes=img_bytes, lbl=lbl):
                    try:
                        fill(j, self._decode_augment(img_bytes), lbl)
                    except Exception as e:  # surfaced after wait
                        errors.append(e)
                self._engine.push(work)
            self._engine.wait_for_all()
            if errors:
                raise errors[0]
            i = len(raws)
        else:
            for img_bytes, lbl in self._gather_batch_raws():
                fill(i, self._decode_augment(img_bytes), lbl)
                i += 1
        pad = self.batch_size - i
        if pad:  # pad with the last valid sample (reference pad semantics)
            for j in range(i, self.batch_size):
                if post is not None:
                    hwc[j] = hwc[i - 1]
                else:
                    data[j] = data[i - 1]
                label[j] = label[i - 1]
        if post is not None:
            d_nd, l_nd = post(hwc, label)
            return DataBatch(data=[d_nd], label=[l_nd], pad=pad,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)
        # batches carry NDArrays like every other DataIter (reference
        # DataBatch contract: .data/.label are NDArray lists); they stay
        # numpy-backed host buffers (from_host) — iterators fill host
        # memory, the executor moves it in ONE host→device transfer
        return DataBatch(data=[ndarray.from_host(data)],
                         label=[ndarray.from_host(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
