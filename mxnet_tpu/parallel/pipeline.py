"""SPMD pipeline parallelism over a mesh axis.

The reference's only model parallelism is manual ``group2ctx`` device
placement with ``_CrossDeviceCopy`` nodes inserted between GPUs
(SURVEY §2.4.3: ``graph_executor.cc:305``, ``example/model-parallel-lstm``)
— a pipeline in spirit (LSTM layers staged across devices) but scheduled
by the dependency engine.  The TPU-native design is the GPipe/SPMD schedule:
every device runs the SAME jitted program for its own stage, activations hop
stage→stage over ICI with ``lax.ppermute``, and microbatches fill the
pipeline so bubbles shrink as ``n_micro / (n_micro + n_stages - 1)``.

``spmd_pipeline`` is differentiable end-to-end (scan + ppermute + where all
have VJPs), so the same schedule serves fwd+bwd — XLA interleaves the
backward ppermutes with compute exactly like the forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def spmd_pipeline(stage_fn, params, xs, axis_name, with_aux=False):
    """Run ``stage_fn`` as a pipeline over ``axis_name``.

    Must be called inside ``shard_map``.  Each device holds its stage's
    params (``params`` pytree leaves have a leading local stage axis of 1).
    ``xs``: (n_micro, mb, ...) microbatched input, replicated across the
    pipeline axis.  Returns (n_micro, mb, ...) outputs, replicated.

    With ``with_aux=True``, ``stage_fn`` returns ``(out, aux_scalar)`` and
    the result is ``(outputs, aux)`` where aux sums each stage's
    per-microbatch mean contribution (fill/drain steps, where a stage holds
    no real microbatch, are masked out).

    Activations must have the same shape/dtype at every stage boundary
    (the ``_CrossDeviceCopy`` contract, made explicit).
    """
    stage = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    n_micro = xs.shape[0]
    steps = n_micro + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]
    local_params = jax.tree_util.tree_map(lambda p: p[0], params)

    state0 = jnp.zeros_like(xs[0])
    out0 = jnp.zeros_like(xs)

    def body(carry, t):
        state, outputs, aux_acc = carry
        inject = xs[jnp.clip(t, 0, n_micro - 1)]
        state = jnp.where(stage == 0, inject, state)
        if with_aux:
            out, aux = stage_fn(local_params, state)
            # stage s holds microbatch t-s at step t; mask fill/drain steps
            mb_idx = t - stage
            valid = (mb_idx >= 0) & (mb_idx < n_micro)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        else:
            out = stage_fn(local_params, state)
        widx = t - (n - 1)
        write = (stage == n - 1) & (widx >= 0)
        outputs = jnp.where(
            write,
            outputs.at[jnp.clip(widx, 0, n_micro - 1)].set(out),
            outputs)
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, outputs, aux_acc), None

    (_, outputs, aux_acc), _ = jax.lax.scan(
        body, (state0, out0, jnp.zeros((), jnp.float32)), jnp.arange(steps))
    # outputs are valid only on the last stage; mask-psum broadcasts them
    # back to every stage (replicated out_spec)
    outputs = jax.lax.psum(jnp.where(stage == n - 1, outputs, 0.0), axis_name)
    if with_aux:
        # sum over stages, mean over microbatches
        return outputs, jax.lax.psum(aux_acc, axis_name) / n_micro
    return outputs


def pipeline_apply(stage_fn, params, x, mesh, n_microbatches,
                   axis_name="pipe", param_specs=None):
    """shard_map wrapper.  ``params`` pytree leaves have a leading stage
    axis of size ``mesh.shape[axis_name]``; ``x``: (batch, ...) is split
    into ``n_microbatches`` along batch.  Returns (batch, ...) outputs."""
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError("batch %d not divisible by n_microbatches %d"
                         % (batch, n_microbatches))
    xs = x.reshape(n_microbatches, batch // n_microbatches, *x.shape[1:])

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda p: P(axis_name), params)

    fn = functools.partial(spmd_pipeline, stage_fn, axis_name=axis_name)
    outs = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)(params, xs)
    return outs.reshape(batch, *outs.shape[2:])


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> single pytree with leading stage axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)
