"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

The second of the two context-parallel strategies (alongside
``parallel.ring``; neither exists in the 2017 reference — SURVEY §5.7
requires "ring attention or all-to-all sequence/context parallelism").

Scheme (DeepSpeed-Ulysses, arXiv:2309.14509, re-expressed with XLA
collectives): activations are sequence-sharded (B, L/n, H, D).  Before
attention, one ``all_to_all`` over the mesh axis re-shards to
head-sharded (B, L, H/n, D) — every device then holds FULL sequences for
a SUBSET of heads, so plain (flash) attention runs locally with exact
softmax and no ring bookkeeping.  A second ``all_to_all`` re-shards the
context back to sequence-sharded.  Communication volume is 4·B·L·H·D/n
per step (Q,K,V in + O out), constant in sequence length per device.

Trade-off vs ring: Ulysses needs ``n_heads % n`` == 0 and moves
activations twice, but each attention is a single dense local kernel (the
Pallas flash path applies unchanged); ring keeps heads whole and overlaps
transfer with compute but pays the online-softmax rescale per hop.  Both
compose with dp/tp over other mesh axes.

Differentiable end-to-end: ``lax.all_to_all`` has a transposable VJP (its
own inverse permutation), so ``jax.grad`` through the wrapped attention
serves training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def _local_attention(q, k, v, causal, softmax_scale):
    """Attention on full local sequences (B, Hl, L, D): the blockwise
    flash kernel, so per-device memory stays O(L·block) and the sp memory
    win is not given back to an L x L score matrix."""
    from ..ops.attention import flash_attention

    return flash_attention(q, k, v, causal=causal,
                           softmax_scale=softmax_scale)


def ulysses_attention(q, k, v, axis_name, causal=False, softmax_scale=None):
    """All-to-all sequence parallelism.  Must run inside ``shard_map``;
    q/k/v are LOCAL sequence shards (B, H, Lc, D) with H divisible by the
    axis size.  Returns the local (B, H, Lc, D) context shard."""
    n = jax.lax.psum(1, axis_name)
    b, h, lc, d = q.shape
    if softmax_scale is None:
        softmax_scale = float(1.0 / np.sqrt(d))

    def seq_to_head(x):
        # (B, H, Lc, D) -> (B, H/n, n*Lc, D): gather sequence, scatter heads
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def head_to_seq(x):
        # inverse reshard: gather heads, scatter sequence
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    o = _local_attention(qh, kh, vh, causal, softmax_scale)
    return head_to_seq(o)


def ulysses_self_attention(q, k, v, mesh, seq_axis="data", causal=False,
                           softmax_scale=None):
    """shard_map wrapper: shard (B, H, L, D) tensors over ``seq_axis`` on
    the sequence dimension and run Ulysses attention across it (drop-in
    alternative to ``ring_self_attention``)."""
    axis_size = mesh.shape[seq_axis]
    if q.shape[1] % axis_size != 0:
        raise ValueError(
            "ulysses: n_heads (%d) must divide by the %r axis size (%d); "
            "use ring_self_attention for head counts that do not shard"
            % (q.shape[1], seq_axis, axis_size))
    spec = P(None, None, seq_axis, None)
    fn = functools.partial(ulysses_attention, axis_name=seq_axis,
                           causal=causal, softmax_scale=softmax_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
