"""Parallelism toolkit — mesh construction + SPMD train steps.

This is the component that replaces the reference's distributed stack most
radically (SURVEY §2.4/§5.8): instead of parameter servers (``ps-lite``) and
device-comm trees (``src/kvstore/comm.h``), parallelism is expressed as
shardings over a ``jax.sharding.Mesh`` and XLA GSPMD compiles the
collectives (psum/all-gather/reduce-scatter) into the training step itself,
riding ICI inside a slice and DCN across slices.

Axes convention (the scaling-book recipe):
  ``data``  — batch (data parallelism; the KVStore('device') analog)
  ``model`` — tensor parallelism (weight shards; layer in/out features)
  ``seq``   — sequence/context parallelism (ring attention; SURVEY §5.7)
"""

from .mesh import make_mesh, named_sharding
from .moe import moe_apply, switch_moe
from .pipeline import pipeline_apply, spmd_pipeline, stack_stage_params
from .ring import ring_attention, ring_self_attention
from .ulysses import ulysses_attention, ulysses_self_attention
from .trainer import SPMDTrainer
from . import lm

__all__ = ["make_mesh", "named_sharding", "SPMDTrainer",
           "ring_attention", "ring_self_attention",
           "ulysses_attention", "ulysses_self_attention",
           "moe_apply", "switch_moe",
           "pipeline_apply", "spmd_pipeline", "stack_stage_params", "lm"]
