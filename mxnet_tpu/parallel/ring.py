"""Ring attention: context/sequence parallelism over the ICI ring.

New capability beyond the 2017 reference (SURVEY §5.7: it has only bucketing
and model-parallel LSTM for long sequences).  Sequence dimension is sharded
over a mesh axis; each device holds (B, H, L/n, D) shards of Q/K/V.  K/V
shards rotate around the ring with ``lax.ppermute`` while every device folds
each visiting block into a running online-softmax accumulator — the same
blockwise core as ``ops.attention``, so memory stays O(L/n) per device and
the sequence length scales linearly with the ring size.

XLA overlaps the ppermute (ICI transfer) with the block's two matmuls (MXU),
which is the whole point of the ring schedule: compute hides communication.

Differentiable end-to-end (scan + ppermute have transposable VJPs), so the
same code path serves training — no separate backward kernel needed.

Per-visiting-shard blocks run through ``ops.attention``'s differentiable
(out, lse) flash pair (round 5): on TPU at kernel-eligible shapes that is
the Pallas kernel (2.6x over the scan core, O(Lc) score memory instead of
the previous dense einsum's O(Lc^2)); elsewhere the blockwise-scan core.
Shards merge by logsumexp reweighting, with gradients flowing through the
merge weights via the pair's lse cotangent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name, causal=False, softmax_scale=None):
    """Blockwise ring attention over ``axis_name``.  Must run inside
    ``shard_map``; q/k/v are the local sequence shards (B, H, Lc, D)."""
    from ..ops.attention import flash_attention_with_lse

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, lc, d = q.shape
    if softmax_scale is None:
        softmax_scale = float(1.0 / np.sqrt(d))

    perm = [(j, (j + 1) % n) for j in range(n)]

    def block(kc, vc, owner):
        """(out, lse) of the local q against one visiting K/V shard.
        With causal masking a visiting shard is either fully visible
        (owner < idx), the diagonal (owner == idx -> causal kernel
        call), or fully hidden (owner > idx -> no kernel at all) — so
        a three-way switch covers every case with no offset mask, and
        hidden steps skip the flash forward AND its backward/residuals
        entirely."""
        def full_b():
            return flash_attention_with_lse(
                q, kc, vc, causal=False, softmax_scale=softmax_scale)

        if not causal:     # python constant: no dead branches traced
            return full_b()

        def diag_b():
            return flash_attention_with_lse(
                q, kc, vc, causal=True, softmax_scale=softmax_scale)

        def hidden_b():
            return (jnp.zeros((b, h, lc, d), q.dtype),
                    jnp.full((b, h, lc), NEG_INF, jnp.float32))

        which = jnp.where(owner == idx, 1, jnp.where(owner > idx, 2, 0))
        return jax.lax.switch(which, (full_b, diag_b, hidden_b))

    def step(carry, s):
        o, lse, kc, vc = carry
        owner = (idx - s) % n                              # shard origin
        o_s, lse_s = block(kc, vc, owner)
        # logsumexp merge of normalized (o, lse) pairs
        m = jnp.maximum(lse, lse_s)
        w1 = jnp.exp(lse - m)
        w2 = jnp.exp(lse_s - m)
        tot = jnp.maximum(w1 + w2, 1e-30)
        o = (o * w1[..., None]
             + o_s.astype(jnp.float32) * w2[..., None]) / tot[..., None]
        lse = m + jnp.log(tot)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, lse, kc, vc), None

    o0 = jnp.zeros((b, h, lc, d), jnp.float32)
    lse0 = jnp.full((b, h, lc), NEG_INF, jnp.float32)
    (o, _lse, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v),
                                      jnp.arange(n))
    # with causal masking the first tokens of rank 0 always see >=1 key;
    # the tot guard above covers empty-ring edge cases
    return o.astype(q.dtype)


def ring_self_attention(q, k, v, mesh, seq_axis="data", causal=False,
                        softmax_scale=None):
    """shard_map wrapper: shard (B, H, L, D) tensors over ``seq_axis`` on
    the sequence dimension and run ring attention across it."""
    spec = P(None, None, seq_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                           softmax_scale=softmax_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
