"""Ring attention: context/sequence parallelism over the ICI ring.

New capability beyond the 2017 reference (SURVEY §5.7: it has only bucketing
and model-parallel LSTM for long sequences).  Sequence dimension is sharded
over a mesh axis; each device holds (B, H, L/n, D) shards of Q/K/V.  K/V
shards rotate around the ring with ``lax.ppermute`` while every device folds
each visiting block into a running online-softmax accumulator — the same
blockwise core as ``ops.attention``, so memory stays O(L/n) per device and
the sequence length scales linearly with the ring size.

XLA overlaps the ppermute (ICI transfer) with the block's two matmuls (MXU),
which is the whole point of the ring schedule: compute hides communication.

Differentiable end-to-end (scan + ppermute have transposable VJPs), so the
same code path serves training — no separate backward kernel needed.

Per-visiting-shard blocks are dense einsums: XLA schedules them on the MXU,
at O(Lc^2) score memory per step (Lc = L/ring).  Swapping in the Pallas
flash kernel (working on hardware since round 5, 2.6x over the scan core)
would drop that to O(Lc) — but the ring merge needs a DIFFERENTIABLE
(out, lse) pair per block, and the kernel's custom_vjp exposes only `out`;
threading lse cotangents through the FA2 backward is the prerequisite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name, causal=False, softmax_scale=None):
    """Blockwise ring attention over ``axis_name``.  Must run inside
    ``shard_map``; q/k/v are the local sequence shards (B, H, Lc, D)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, lc, d = q.shape
    if softmax_scale is None:
        softmax_scale = float(1.0 / np.sqrt(d))

    qf = q.astype(jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]
    q_pos = idx * lc + jnp.arange(lc)[:, None]            # global q positions

    def step(carry, s):
        o, m, l, kc, vc = carry
        owner = (idx - s) % n                              # shard origin
        kpos = owner * lc + jnp.arange(lc)[None, :]
        sc = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32))
        sc = sc * softmax_scale
        if causal:
            sc = jnp.where(q_pos >= kpos, sc, NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(sc - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o_new, m_new, l_new, kc, vc), None

    o0 = jnp.zeros((b, h, lc, d), jnp.float32)
    m0 = jnp.full((b, h, lc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lc), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(step, (o0, m0, l0, k, v),
                                      jnp.arange(n))
    # with causal masking the first tokens of rank 0 always see >=1 key,
    # so l>0 everywhere; the maximum is a guard for empty-ring edge cases
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype)


def ring_self_attention(q, k, v, mesh, seq_axis="data", causal=False,
                        softmax_scale=None):
    """shard_map wrapper: shard (B, H, L, D) tensors over ``seq_axis`` on
    the sequence dimension and run ring attention across it."""
    spec = P(None, None, seq_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                           softmax_scale=softmax_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
