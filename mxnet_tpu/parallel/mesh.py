"""Mesh construction helpers."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "named_sharding", "PartitionSpec"]


def make_mesh(n_devices=None, axis_names=("data", "model"), shape=None,
              devices=None):
    """Build a Mesh over the first ``n_devices`` JAX devices.

    ``shape`` defaults to putting everything on the first axis except a
    factor-2 (or given) model axis when the count allows it.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        if len(axis_names) == 1:
            shape = (n,)
        elif len(axis_names) == 2:
            model = 2 if (n % 2 == 0 and n >= 4) else 1
            shape = (n // model, model)
        else:
            shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError("mesh shape %s != %d devices" % (shape, n))
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names)


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, PartitionSpec(*spec))
