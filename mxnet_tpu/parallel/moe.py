"""Mixture-of-Experts with expert parallelism (GShard/Switch style).

Not present in the 2017 reference (SURVEY §7 step 9 lists MoE/EP as a
new-capability hook).  Experts are sharded over a mesh axis; tokens are
sharded over the same axis on their batch dimension.  Routing is top-1
(Switch) with a static per-source capacity so every shape is fixed under
``jit``: dispatch/combine are one-hot einsums (MXU-friendly — no scatter),
and the token exchange is a single ``lax.all_to_all`` each way over ICI —
the canonical EP schedule.

Everything is differentiable; the load-balancing auxiliary loss
(Switch: E * Σ_e frac_tokens_e · mean_prob_e) is returned alongside.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def switch_moe(x, w_gate, w_up, w_down, axis_name, capacity_factor=2.0):
    """Top-1 MoE over local tokens.  Must run inside ``shard_map``.

    x: (T, D) local tokens; w_gate: (D, E) replicated;
    w_up: (E_local, D, H), w_down: (E_local, H, D) local expert shards.
    Returns (y, aux_loss): y (T, D), aux_loss scalar (psum-reduced mean).
    """
    n = jax.lax.psum(1, axis_name)
    e_local = w_up.shape[0]
    e = e_local * n
    t, d = x.shape

    logits = x @ w_gate                                   # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate = probs.max(axis=-1)                             # (T,)
    eidx = probs.argmax(axis=-1)                          # (T,)

    # static capacity per (source device, expert)
    cap = max(1, int(capacity_factor * t / e))

    onehot = jax.nn.one_hot(eidx, e, dtype=jnp.float32)   # (T, E)
    pos = jnp.cumsum(onehot, axis=0) - 1.0                # position in expert
    keep = onehot * (pos < cap)                           # drop overflow
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        pos.astype(jnp.int32), cap, dtype=jnp.float32)    # (T, E, C)
    combine = dispatch * gate[:, None, None]              # (T, E, C)

    # tokens -> per-expert buffers, exchange over the expert axis
    exp_in = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    exp_in = exp_in.reshape(n, e_local, cap, d)
    exp_in = jax.lax.all_to_all(exp_in, axis_name, split_axis=0,
                                concat_axis=0)            # (n_src, El, C, D)
    exp_in = exp_in.transpose(1, 0, 2, 3).reshape(e_local, n * cap, d)

    h = jax.nn.relu(jnp.einsum("esd,edh->esh", exp_in,
                               w_up.astype(jnp.float32)))
    out = jnp.einsum("esh,ehd->esd", h, w_down.astype(jnp.float32))

    # route results back to the source devices
    out = out.reshape(e_local, n, cap, d).transpose(1, 0, 2, 3)
    out = jax.lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0)
    out = out.reshape(e, cap, d)                          # global expert view
    y = jnp.einsum("tec,ecd->td", combine, out).astype(x.dtype)

    # Switch load-balancing loss, averaged over all devices
    frac_tokens = onehot.mean(axis=0)                     # (E,)
    mean_prob = probs.mean(axis=0)                        # (E,)
    aux = e * jnp.sum(frac_tokens * mean_prob)
    aux = jax.lax.pmean(aux, axis_name)
    return y, aux


def moe_apply(x, w_gate, w_up, w_down, mesh, axis_name="model",
              capacity_factor=2.0):
    """shard_map wrapper: x (tokens, D) sharded over ``axis_name`` on dim 0;
    experts (dim 0 of w_up/w_down) sharded over the same axis."""
    fn = functools.partial(switch_moe, axis_name=axis_name,
                           capacity_factor=capacity_factor)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(axis_name, None), P(), P(axis_name), P(axis_name)),
        out_specs=(P(axis_name, None), P()),
        check_vma=False)(x, w_gate, w_up, w_down)
