"""SPMDTrainer — a fully-fused sharded training step over a device mesh.

NOTE: the user-facing surface for dp×tp training is ``mx.mod.Module`` with
``context=<jax Mesh>`` + ``shard_rules`` (reference users never see a second
trainer class); SPMDTrainer remains as the low-level engine and for
experiments that bypass the Module bookkeeping.

One jitted function per (symbol, mesh, shardings): forward + backward +
SGD-momentum update, with parameter/optimizer-state buffers donated.  This
is the ``Module.fit`` hot path distilled to its TPU-native core: the
reference needs engine scheduling + kvstore push/pull per step
(SURVEY §3.1); here the whole step including the gradient allreduce is one
XLA program.

Sharding rules:
* data/label: ``P('data', ...)`` — batch split (DP).
* parameters: replicated by default; a ``tp_rules`` list of
  ``(name_regex, PartitionSpec)`` shards chosen weights over ``model`` (TP).
  XLA inserts the all-gathers/reduce-scatters those shards imply.
"""

from __future__ import annotations

import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..executor import _graph_forward

__all__ = ["SPMDTrainer"]


class SPMDTrainer:
    def __init__(self, symbol, mesh, data_names=("data",),
                 label_names=("softmax_label",), tp_rules=(),
                 lr=0.01, momentum=0.9, wd=0.0, dtype=np.float32):
        self.symbol = symbol
        self.mesh = mesh
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.tp_rules = [(re.compile(p), spec) for p, spec in tp_rules]
        self.lr = lr
        self.momentum = momentum
        self.wd = wd
        self.dtype = dtype
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.param_names = [n for n in self.arg_names
                            if n not in self.data_names + self.label_names]
        self._step = None
        self.params = None
        self.aux = None
        self.moms = None

    # -- placement --------------------------------------------------------
    def param_spec(self, name):
        for prog, spec in self.tp_rules:
            if prog.match(name):
                return spec
        return P()

    def _put(self, value, spec):
        return jax.device_put(value, NamedSharding(self.mesh, spec))

    def init(self, data_shapes, seed=0):
        """Infer shapes, initialize and place parameters over the mesh."""
        var_shape, _vd, _ = self.symbol._infer_shapes_full(dict(data_shapes))
        rs = np.random.RandomState(seed)
        self.params = {}
        self.moms = {}
        for n in self.param_names:
            s = var_shape[n]
            if n.endswith("_bias") or n.endswith("_beta") \
                    or n.endswith("moving_mean"):
                v = np.zeros(s, self.dtype)
            elif n.endswith("_gamma") or n.endswith("moving_var"):
                v = np.ones(s, self.dtype)
            else:
                fan_in = int(np.prod(s[1:])) or 1
                v = (rs.normal(0, np.sqrt(2.0 / fan_in), s)
                     .astype(self.dtype))
            spec = self.param_spec(n)
            self.params[n] = self._put(v, spec)
            self.moms[n] = self._put(np.zeros(s, self.dtype), spec)
        self.aux = {}
        for n in self.aux_names:
            s = var_shape[n]
            v = np.ones(s, self.dtype) if n.endswith("moving_var") \
                else np.zeros(s, self.dtype)
            self.aux[n] = self._put(v, P())
        return self

    def place_batch(self, arrays, names=None):
        names = names or (self.data_names + self.label_names)
        return {n: self._put(np.asarray(a), P("data"))
                for n, a in zip(names, arrays)}

    # -- the fused step ----------------------------------------------------
    def _build(self):
        symbol = self.symbol
        lr, momentum, wd = self.lr, self.momentum, self.wd
        aux_names = list(self.aux_names)

        def step(params, aux, moms, batch, rng):
            def g(p):
                vals = dict(batch)
                vals.update(p)
                outs, new_aux = _graph_forward(symbol, vals, aux, True, rng)
                return tuple(outs), new_aux

            outs, vjp_fn, new_aux = jax.vjp(g, params, has_aux=True)
            (grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
            new_params, new_moms = {}, {}
            for n, p in params.items():
                gr = grads[n] + wd * p
                if momentum != 0.0:
                    m = momentum * moms[n] - lr * gr
                    new_moms[n] = m
                    new_params[n] = p + m
                else:
                    new_moms[n] = moms[n]
                    new_params[n] = p - lr * gr
            new_aux_full = {n: new_aux.get(n, aux[n]) for n in aux_names}
            return outs, new_params, new_aux_full, new_moms

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def train_step(self, batch, rng=None):
        """Run one fused step; updates self.params/aux/moms in place."""
        if self._step is None:
            self._step = self._build()
        if rng is None:
            rng = jax.random.PRNGKey(0)
        outs, self.params, self.aux, self.moms = self._step(
            self.params, self.aux, self.moms, batch, rng)
        return outs
