"""Composite-parallel transformer LM: dp x tp x pp x sp x ep in one step.

This is the parallelism flagship: a MoE transformer language model whose
training step composes every strategy the framework offers on one mesh
(axes ``data``/``model``/``pipe``):

* **dp** — batch sharded over ``data``; gradient psum inserted by the
  shard_map transpose / GSPMD.
* **tp** — vocab-sharded embedding + output head over ``model`` (Megatron
  column split; XLA inserts the logits all-gather / psum).
* **pp** — transformer blocks staged over ``pipe`` via
  :func:`mxnet_tpu.parallel.pipeline.spmd_pipeline` (GPipe microbatching).
* **sp** — sequence sharded over ``model`` inside each stage; attention is
  :func:`mxnet_tpu.parallel.ring.ring_attention` over the same axis
  (Megatron-SP style: sequence parallelism rides the TP axis).
* **ep** — each stage's FFN is a Switch MoE with experts sharded over
  ``model`` (:func:`mxnet_tpu.parallel.moe.switch_moe`, all_to_all token
  exchange).

The whole step (fwd + bwd + SGD update) is ONE jitted SPMD program — the
TPU answer to the reference's engine-scheduled multi-GPU pipeline
(``example/model-parallel-lstm``) and parameter-server update loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .moe import switch_moe
from .pipeline import spmd_pipeline
from .ring import ring_attention
from .ulysses import ulysses_attention


def init_params(rng, vocab, embed, heads, ffn_hidden, n_experts, n_stages,
                dtype=jnp.float32):
    if embed % heads:
        raise ValueError("embed=%d not divisible by heads=%d" % (embed, heads))
    rs = np.random.RandomState(rng)

    def nrm(*shape, s=0.05):
        return jnp.asarray(rs.normal(0, s, shape).astype(np.float32), dtype=dtype)

    return {
        "embed": nrm(vocab, embed),
        "head": nrm(embed, vocab),
        "stages": {
            "qkv_w": nrm(n_stages, 3 * embed, embed),
            "out_w": nrm(n_stages, embed, embed),
            "gate_w": nrm(n_stages, embed, n_experts),
            "up_w": nrm(n_stages, n_experts, embed, ffn_hidden),
            "down_w": nrm(n_stages, n_experts, ffn_hidden, embed),
            "ln1": jnp.ones((n_stages, embed), dtype),
            "ln2": jnp.ones((n_stages, embed), dtype),
        },
    }


def param_specs():
    """Axis names are fixed: ``model``/``pipe``/``data`` (matching the
    collectives hardcoded in ``_stage_fn``)."""
    return {
        # embed replicated: the token gather is then device-local, avoiding
        # a pathological GSPMD reshard of its output; the head carries TP
        "embed": P(None, None),
        "head": P(None, "model"),            # tp: vocab sharded
        "stages": {
            "qkv_w": P("pipe"),
            "out_w": P("pipe"),
            "gate_w": P("pipe"),
            "up_w": P("pipe", "model"),      # ep: experts sharded
            "down_w": P("pipe", "model"),
            "ln1": P("pipe"),
            "ln2": P("pipe"),
        },
    }


def _rmsnorm(x, g):
    return x * g * jax.lax.rsqrt((x.astype(jnp.float32) ** 2).mean(-1, keepdims=True)
                                 + 1e-6).astype(x.dtype)


def _stage_fn(params, x, *, heads, capacity_factor, seq_impl="ring"):
    """One transformer block on local shards: x (mb, L_local, E).

    ``seq_impl``: sequence-parallel attention strategy — ``"ring"``
    (ppermute online-softmax) or ``"ulysses"`` (all-to-all head reshard;
    needs heads divisible by the model-axis size).
    """
    mb, lloc, e = x.shape
    hd = e // heads

    h = _rmsnorm(x, params["ln1"])
    qkv = jnp.einsum("ble,fe->blf", h, params["qkv_w"])
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def to_heads(t):
        return t.reshape(mb, lloc, heads, hd).transpose(0, 2, 1, 3)

    attn = ulysses_attention if seq_impl == "ulysses" else ring_attention
    att = attn(to_heads(q), to_heads(k), to_heads(v),
               axis_name="model", causal=True)
    att = att.transpose(0, 2, 1, 3).reshape(mb, lloc, e)
    x = x + jnp.einsum("ble,fe->blf", att, params["out_w"])

    h = _rmsnorm(x, params["ln2"])
    tokens = h.reshape(mb * lloc, e)
    moe_out, aux = switch_moe(tokens, params["gate_w"], params["up_w"],
                              params["down_w"], axis_name="model",
                              capacity_factor=capacity_factor)
    return x + moe_out.reshape(mb, lloc, e), aux


def make_train_step(mesh, heads, n_microbatches, lr=0.1, capacity_factor=4.0,
                    aux_loss_coef=0.01, seq_impl="ring"):
    """Returns jitted ``(params, tokens, labels) -> (params, loss)``.

    tokens/labels: (B, L) int32, B sharded over ``data``.  The Switch
    load-balancing loss (summed over stages) is added with
    ``aux_loss_coef`` — top-1 routing collapses onto few experts without it.
    ``seq_impl`` picks the sequence-parallel attention: ``"ring"`` or
    ``"ulysses"`` (heads must divide by the model-axis size).
    """
    if seq_impl not in ("ring", "ulysses"):
        raise ValueError("seq_impl must be 'ring' or 'ulysses', got %r"
                         % (seq_impl,))
    if seq_impl == "ulysses" and heads % mesh.shape["model"] != 0:
        raise ValueError(
            "seq_impl='ulysses' needs heads (%d) divisible by the model "
            "axis size (%d); use seq_impl='ring'"
            % (heads, mesh.shape["model"]))
    stage = functools.partial(_stage_fn, heads=heads,
                              capacity_factor=capacity_factor,
                              seq_impl=seq_impl)

    def pipe_body(stage_params, xs):
        out, aux = spmd_pipeline(stage, stage_params, xs, "pipe",
                                 with_aux=True)
        # aux is psum'd over pipe and pmean'd over model (switch_moe);
        # average over data shards so the P() out_spec is truly replicated
        return out, jax.lax.pmean(aux, "data")

    specs = param_specs()

    def loss_fn(params, tokens, labels):
        x = params["embed"][tokens]            # (B, L, E) gather, tp-sharded
        b, l, e = x.shape
        mb = b // n_microbatches
        xs = x.reshape(n_microbatches, mb, l, e)

        out, aux = jax.shard_map(
            pipe_body, mesh=mesh,
            in_specs=(specs["stages"], P(None, "data", "model", None)),
            out_specs=(P(None, "data", "model", None), P()),
            check_vma=False)(params["stages"], xs)
        out = out.reshape(b, l, e)

        logits = jnp.einsum("ble,ev->blv", out, params["head"])
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)
        return nll.mean() + aux_loss_coef * aux

    def train_step(params, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g.astype(p.dtype),
                                        params, grads)
        return params, loss

    pspec_sharding = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs)
    data_sharding = NamedSharding(mesh, P("data", None))
    return jax.jit(
        train_step,
        in_shardings=(pspec_sharding, data_sharding, data_sharding),
        out_shardings=(pspec_sharding, NamedSharding(mesh, P())),
        donate_argnums=(0,))
