"""Evaluation metrics (``mx.metric``). Reference: ``python/mxnet/metric.py``."""

from __future__ import annotations

import numpy as _np

from .base import MXNetError, Registry
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss",
           "CustomMetric", "np", "create", "check_label_shapes"]

registry = Registry("metric")


def check_label_shapes(labels, preds, shape=0):
    """reference ``metric.py:10``"""
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels %s does not match shape of predictions %s"
            % (label_shape, pred_shape))


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class EvalMetric:
    """reference ``metric.py:20``"""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def get(self):
        if self.num is None:
            value = self.sum_metric / self.num_inst if self.num_inst != 0 \
                else float("nan")
            return (self.name, value)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [s / n if n != 0 else float("nan")
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


@registry.register
class CompositeEvalMetric(EvalMetric):
    """reference ``metric.py:86``"""

    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name)
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, results = [], []
        for metric in self.metrics:
            name, result = metric.get()
            names.append(name) if not isinstance(name, list) \
                else names.extend(name)
            results.append(result) if not isinstance(result, list) \
                else results.extend(result)
        return (names, results)


@registry.register
class Accuracy(EvalMetric):
    """reference ``metric.py:132``"""

    def __init__(self, axis=1, **kwargs):
        super().__init__("accuracy")
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            p = _as_np(pred_label)
            if p.ndim > 1 and p.shape[-1] > 1 and p.ndim >= 2:
                p = _np.argmax(p, axis=self.axis if p.ndim > self.axis else -1)
            lab = _as_np(label).astype("int32").flatten()
            p = p.astype("int32").flatten()
            check_label_shapes(lab, p, shape=1)
            self.sum_metric += float((p == lab).sum())
            self.num_inst += len(p)


@registry.register
class TopKAccuracy(EvalMetric):
    """reference ``metric.py:152``"""

    def __init__(self, top_k=1, **kwargs):
        super().__init__("top_k_accuracy")
        self.top_k = top_k
        assert self.top_k > 1, "Use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            p = _np.argsort(_as_np(pred_label).astype("float32"), axis=1)
            lab = _as_np(label).astype("int32")
            num_samples = p.shape[0]
            num_classes = p.shape[1]
            top_k = min(num_classes, self.top_k)
            for j in range(top_k):
                self.sum_metric += float(
                    (p[:, num_classes - 1 - j].flatten() ==
                     lab.flatten()).sum())
            self.num_inst += num_samples


@registry.register
class F1(EvalMetric):
    """reference ``metric.py:183`` (binary)"""

    def __init__(self, **kwargs):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype("int32")
            pred_label = _np.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(_np.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary"
                                 " classification.")
            tp = fp = fn = 0.0
            for y_pred, y_true in zip(pred_label, label):
                if y_pred == 1 and y_true == 1:
                    tp += 1.0
                elif y_pred == 1 and y_true == 0:
                    fp += 1.0
                elif y_pred == 0 and y_true == 1:
                    fn += 1.0
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            f1 = 2 * precision * recall / (precision + recall) \
                if precision + recall > 0 else 0.0
            self.sum_metric += f1
            self.num_inst += 1


@registry.register
class Perplexity(EvalMetric):
    """reference ``metric.py:230``"""

    def __init__(self, ignore_label=None, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            assert label.size == pred.size / pred.shape[-1], \
                "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
            label = label.reshape((label.size,)).astype("int32")
            probs = pred.reshape(-1, pred.shape[-1])[
                _np.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= float(_np.sum(_np.log(_np.maximum(1e-10, probs))))
            num += label.size
        self.sum_metric += float(_np.exp(loss / num)) * num
        self.num_inst += num

    def get(self):
        # reference computes exp(total_nll/total_n); approximate via weighted
        # mean of per-batch perplexities accumulated above
        return super().get()


@registry.register
class MAE(EvalMetric):
    """reference ``metric.py:280``"""

    def __init__(self, **kwargs):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@registry.register
class MSE(EvalMetric):
    """reference ``metric.py:297``"""

    def __init__(self, **kwargs):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@registry.register
class RMSE(EvalMetric):
    """reference ``metric.py:317``"""

    def __init__(self, **kwargs):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += float(
                _np.sqrt(((label - pred) ** 2.0).mean()))
            self.num_inst += 1


@registry.register
class CrossEntropy(EvalMetric):
    """reference ``metric.py:335``"""

    def __init__(self, eps=1e-8, **kwargs):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel()
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), _np.int64(label)]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@registry.register
class Loss(EvalMetric):
    """Mean of raw outputs (for MakeLoss graphs)."""

    def __init__(self, **kwargs):
        super().__init__("loss")

    def update(self, _, preds):
        for pred in preds:
            pred = _as_np(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size


@registry.register
class Torch(Loss):
    """alias kept for reference-API parity"""

    def __init__(self, name="torch", **kwargs):
        EvalMetric.__init__(self, name)


@registry.register
class CustomMetric(EvalMetric):
    """reference ``metric.py:370``"""

    def __init__(self, feval, name=None, allow_extra_outputs=False, **kwargs):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _as_np(label)
            pred = _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """reference ``metric.py`` np() — wrap a numpy feval."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """reference ``metric.py`` create"""
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, **kwargs))
        return composite
    if metric in ("acc",):
        metric = "accuracy"
    if metric in ("ce",):
        metric = "crossentropy"
    # underscore spellings used throughout the reference examples
    metric = str(metric).lower()
    metric = {"top_k_accuracy": "topkaccuracy",
              "cross-entropy": "crossentropy"}.get(metric, metric)
    return registry.create(metric, **kwargs)
