"""Evaluation metrics (``mx.metric``). Reference: ``python/mxnet/metric.py``.

Two update paths:

* **host** (the reference semantics): ``update(labels, preds)`` pulls the
  arrays to host and accumulates python floats — works for any metric,
  costs one device→host sync per batch.
* **device** (the sync-free ``Module.fit`` path, docs/how_to/perf.md):
  metrics that define ``_device_batch_stats`` reduce each batch to two
  scalars *(sum_metric delta, num_inst delta)* **on device**; a
  :class:`DeviceMetric` wrapper dispatches one tiny jitted accumulation
  per batch into a device-resident buffer, and only ``get()`` /
  ``get_name_value()`` syncs (folding the buffer back into the wrapped
  host metric, so mixed host/device updates still add up).  ``fit`` and
  ``score`` auto-wrap eligible metrics; custom/host-only metrics fall
  back to the host path (``MXNET_DEVICE_METRIC=0`` disables globally).
"""

from __future__ import annotations

import os as _os

import numpy as _np

from .base import MXNetError, Registry
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss",
           "CustomMetric", "DeviceMetric", "np", "create",
           "check_label_shapes", "device_capable", "device_enabled",
           "as_device"]

registry = Registry("metric")


def check_label_shapes(labels, preds, shape=0):
    """reference ``metric.py:10``"""
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels %s does not match shape of predictions %s"
            % (label_shape, pred_shape))


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)  # host-sync: ok — host metric path


class EvalMetric:
    """reference ``metric.py:20``"""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def get(self):
        if self.num is None:
            value = self.sum_metric / self.num_inst if self.num_inst != 0 \
                else float("nan")
            return (self.name, value)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [s / n if n != 0 else float("nan")
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    # -- state capture (preemption-tolerant fit) --------------------------
    def get_state(self):
        """JSON-able accumulator state for mid-epoch checkpoints
        (docs/resilience.md "Preemption & exact resume"): restoring it
        via :meth:`set_state` and continuing from batch k+1 reproduces
        an uninterrupted epoch's final value exactly.  Accumulators are
        coerced to plain Python numbers — custom ``update()``
        implementations routinely leave numpy scalars in ``sum_metric``,
        which would poison the snapshot manifest's ``json.dumps``."""
        def _py(v):
            if isinstance(v, list):
                return [_py(x) for x in v]
            # lint: ok[host-sync] host numpy scalars at snapshot capture — no device buffer involved
            return v.item() if hasattr(v, "item") else v

        return {"sum_metric": _py(self.sum_metric),
                "num_inst": _py(self.num_inst)}

    def set_state(self, state):
        """Inverse of :meth:`get_state` (after a :meth:`reset`)."""
        self.sum_metric = state["sum_metric"]
        self.num_inst = state["num_inst"]

    # -- device path (sync-free fit) --------------------------------------
    def _device_batch_stats(self, labels, preds):
        """Per-batch sufficient statistics as traced jax scalars:
        ``(sum_metric delta, num_inst delta)``.  Subclasses override with
        pure ``jnp`` math (runs inside :class:`DeviceMetric`'s jit); the
        base sentinel means "no device path" and the metric stays on the
        host ``update()`` fallback."""
        raise NotImplementedError("%s has no device path" % self.name)


@registry.register
class CompositeEvalMetric(EvalMetric):
    """reference ``metric.py:86``"""

    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name)
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, results = [], []
        for metric in self.metrics:
            name, result = metric.get()
            names.append(name) if not isinstance(name, list) \
                else names.extend(name)
            results.append(result) if not isinstance(result, list) \
                else results.extend(result)
        return (names, results)

    def get_state(self):
        return {"children": [m.get_state() for m in self.metrics]}

    def set_state(self, state):
        children = state["children"]
        if len(children) != len(self.metrics):
            raise MXNetError(
                "composite metric state has %d children, metric has %d"
                % (len(children), len(self.metrics)))
        for metric, child in zip(self.metrics, children):
            metric.set_state(child)


@registry.register
class Accuracy(EvalMetric):
    """reference ``metric.py:132``"""

    def __init__(self, axis=1, **kwargs):
        super().__init__("accuracy")
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            p = _as_np(pred_label)
            if p.ndim > 1 and p.shape[-1] > 1 and p.ndim >= 2:
                p = _np.argmax(p, axis=self.axis if p.ndim > self.axis else -1)
            lab = _as_np(label).astype("int32").flatten()
            p = p.astype("int32").flatten()
            check_label_shapes(lab, p, shape=1)
            self.sum_metric += float((p == lab).sum())
            self.num_inst += len(p)

    def _device_batch_stats(self, labels, preds):
        import jax.numpy as jnp

        check_label_shapes(labels, preds)
        s, n = jnp.float32(0.0), 0
        for label, p in zip(labels, preds):
            if p.ndim > 1 and p.shape[-1] > 1:
                p = jnp.argmax(p, axis=self.axis if p.ndim > self.axis
                               else -1)
            lab = label.astype(jnp.int32).reshape(-1)
            p = p.astype(jnp.int32).reshape(-1)
            check_label_shapes(lab, p, shape=1)
            s = s + (p == lab).sum().astype(jnp.float32)
            n += p.size
        return s, jnp.float32(n)


@registry.register
class TopKAccuracy(EvalMetric):
    """reference ``metric.py:152``"""

    def __init__(self, top_k=1, **kwargs):
        super().__init__("top_k_accuracy")
        self.top_k = top_k
        assert self.top_k > 1, "Use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            p = _np.argsort(_as_np(pred_label).astype("float32"), axis=1)
            lab = _as_np(label).astype("int32")
            num_samples = p.shape[0]
            num_classes = p.shape[1]
            top_k = min(num_classes, self.top_k)
            for j in range(top_k):
                self.sum_metric += float(
                    (p[:, num_classes - 1 - j].flatten() ==
                     lab.flatten()).sum())
            self.num_inst += num_samples

    def _device_batch_stats(self, labels, preds):
        # device argsort is stable where numpy's default is not; on exact
        # logit ties the top-k *membership* can differ from the host path
        import jax.numpy as jnp

        check_label_shapes(labels, preds)
        s, n = jnp.float32(0.0), 0
        for label, pred in zip(labels, preds):
            p = jnp.argsort(pred.astype(jnp.float32), axis=1)
            lab = label.astype(jnp.int32).reshape(-1)
            num_classes = p.shape[1]
            for j in range(min(num_classes, self.top_k)):
                s = s + (p[:, num_classes - 1 - j].reshape(-1) == lab) \
                    .sum().astype(jnp.float32)
            n += p.shape[0]
        return s, jnp.float32(n)


@registry.register
class F1(EvalMetric):
    """reference ``metric.py:183`` (binary)"""

    def __init__(self, **kwargs):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype("int32")
            pred_label = _np.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(_np.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary"
                                 " classification.")
            tp = fp = fn = 0.0
            for y_pred, y_true in zip(pred_label, label):
                if y_pred == 1 and y_true == 1:
                    tp += 1.0
                elif y_pred == 1 and y_true == 0:
                    fp += 1.0
                elif y_pred == 0 and y_true == 1:
                    fn += 1.0
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            f1 = 2 * precision * recall / (precision + recall) \
                if precision + recall > 0 else 0.0
            self.sum_metric += f1
            self.num_inst += 1


@registry.register
class Perplexity(EvalMetric):
    """reference ``metric.py:230``"""

    def __init__(self, ignore_label=None, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            assert label.size == pred.size / pred.shape[-1], \
                "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
            label = label.reshape((label.size,)).astype("int32")
            probs = pred.reshape(-1, pred.shape[-1])[
                _np.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= float(_np.sum(_np.log(_np.maximum(1e-10, probs))))
            num += label.size
        self.sum_metric += float(_np.exp(loss / num)) * num
        self.num_inst += num

    def get(self):
        # reference computes exp(total_nll/total_n); approximate via weighted
        # mean of per-batch perplexities accumulated above
        return super().get()


@registry.register
class MAE(EvalMetric):
    """reference ``metric.py:280``"""

    def __init__(self, **kwargs):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1

    def _device_batch_stats(self, labels, preds):
        import jax.numpy as jnp

        check_label_shapes(labels, preds)
        s, n = jnp.float32(0.0), 0
        for label, pred in zip(labels, preds):
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            s = s + jnp.abs(label - pred).mean().astype(jnp.float32)
            n += 1
        return s, jnp.float32(n)


@registry.register
class MSE(EvalMetric):
    """reference ``metric.py:297``"""

    def __init__(self, **kwargs):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2.0).mean())
            self.num_inst += 1

    def _device_batch_stats(self, labels, preds):
        import jax.numpy as jnp

        check_label_shapes(labels, preds)
        s, n = jnp.float32(0.0), 0
        for label, pred in zip(labels, preds):
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            s = s + ((label - pred) ** 2.0).mean().astype(jnp.float32)
            n += 1
        return s, jnp.float32(n)


@registry.register
class RMSE(EvalMetric):
    """reference ``metric.py:317``"""

    def __init__(self, **kwargs):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += float(
                _np.sqrt(((label - pred) ** 2.0).mean()))
            self.num_inst += 1

    def _device_batch_stats(self, labels, preds):
        import jax.numpy as jnp

        check_label_shapes(labels, preds)
        s, n = jnp.float32(0.0), 0
        for label, pred in zip(labels, preds):
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            s = s + jnp.sqrt(((label - pred) ** 2.0).mean()) \
                .astype(jnp.float32)
            n += 1
        return s, jnp.float32(n)


@registry.register
class CrossEntropy(EvalMetric):
    """reference ``metric.py:335``"""

    def __init__(self, eps=1e-8, **kwargs):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel()
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), _np.int64(label)]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]

    def _device_batch_stats(self, labels, preds):
        import jax.numpy as jnp

        check_label_shapes(labels, preds)
        s, n = jnp.float32(0.0), 0
        for label, pred in zip(labels, preds):
            lab = label.astype(jnp.int32).reshape(-1)
            assert lab.shape[0] == pred.shape[0]
            prob = pred[jnp.arange(lab.shape[0]), lab]
            # jax gather CLAMPS out-of-range indices where numpy raises —
            # surface corrupt labels as NaN instead of a plausible value
            prob = jnp.where((lab >= 0) & (lab < pred.shape[-1]),
                             prob, jnp.nan)
            s = s + (-jnp.log(prob + self.eps)).sum().astype(jnp.float32)
            n += lab.shape[0]
        return s, jnp.float32(n)


@registry.register
class Loss(EvalMetric):
    """Mean of raw outputs (for MakeLoss graphs)."""

    def __init__(self, **kwargs):
        super().__init__("loss")

    def update(self, _, preds):
        for pred in preds:
            pred = _as_np(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size

    def _device_batch_stats(self, labels, preds):
        import jax.numpy as jnp

        s, n = jnp.float32(0.0), 0
        for pred in preds:
            s = s + pred.sum().astype(jnp.float32)
            n += pred.size
        return s, jnp.float32(n)


@registry.register
class Torch(Loss):
    """alias kept for reference-API parity"""

    def __init__(self, name="torch", **kwargs):
        EvalMetric.__init__(self, name)


@registry.register
class CustomMetric(EvalMetric):
    """reference ``metric.py:370``"""

    def __init__(self, feval, name=None, allow_extra_outputs=False, **kwargs):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _as_np(label)
            pred = _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


# ---------------------------------------------------------------------------
# device-resident accumulation (the sync-free Module.fit path)
# ---------------------------------------------------------------------------
def _leaves_of(metric):
    """Flatten a (possibly composite) metric into its leaf metrics, in
    ``get()`` order."""
    if isinstance(metric, CompositeEvalMetric):
        return [leaf for child in metric.metrics
                for leaf in _leaves_of(child)]
    return [metric]


def _defining_class(cls, name):
    for c in cls.__mro__:
        if name in vars(c):
            return c
    return None


def device_capable(metric):
    """True when every leaf of ``metric`` has a device stats path (and a
    scalar accumulator) — i.e. :class:`DeviceMetric` can wrap it.

    A subclass that overrides ``update()`` with custom semantics but
    inherits a builtin's ``_device_batch_stats`` is NOT capable: the
    device path would silently compute the parent's statistics and
    bypass the override, so the stats definition must live at (or below)
    the class that defines ``update()``."""
    leaves = _leaves_of(metric)
    if not leaves:
        return False
    for leaf in leaves:
        if leaf.num is not None:
            return False
        c_stats = _defining_class(type(leaf), "_device_batch_stats")
        if c_stats is None or c_stats is EvalMetric:
            return False
        c_update = _defining_class(type(leaf), "update")
        if c_update is not None and not issubclass(c_stats, c_update):
            return False
    return True


def device_enabled():
    """Global switch for the device metric path (``MXNET_DEVICE_METRIC``,
    default on; ``0`` forces every metric through host ``update()``)."""
    return _os.environ.get("MXNET_DEVICE_METRIC", "1") \
        not in ("0", "false")


def as_device(metric):
    """Wrap ``metric`` in a :class:`DeviceMetric` when eligible and
    enabled; return it unchanged otherwise (the host fallback).  The
    wrapper is cached on the metric, so repeated wrapping (``score``
    every validation epoch) reuses the accumulated jit cache instead of
    retracing."""
    if isinstance(metric, DeviceMetric):
        return metric
    if device_enabled() and device_capable(metric):
        wrapper = getattr(metric, "_device_wrapper", None)
        if wrapper is None:
            wrapper = DeviceMetric(metric)
            metric._device_wrapper = wrapper
        return wrapper
    return metric


def _device_raw(x):
    """Underlying buffer for the device path: jax array for device-backed
    NDArrays, raw numpy for host-backed ones (placed by the caller)."""
    return x._transfer_src() if isinstance(x, NDArray) else x


class DeviceMetric(EvalMetric):
    """Device-resident accumulator around a host :class:`EvalMetric`.

    ``update()`` dispatches ONE tiny jitted reduction per batch — each
    leaf metric's ``_device_batch_stats`` sufficient statistics, summed
    into a ``(n_leaves, 2)`` device buffer with a donated accumulator —
    and returns without blocking; the XLA computation overlaps the next
    step's host work exactly like the training dispatch itself.
    ``get()``/``get_name_value()`` are the only sync points: the buffer
    is pulled once (telemetry ``sync`` phase), folded *into* the wrapped
    leaves' host ``sum_metric``/``num_inst``, and cleared — so mixed
    host/device updates, callback-cadence reads (``Speedometer``) and
    user-held references to the wrapped metric all stay consistent.

    Accumulation runs in float32 on device; versus the host path's
    float64 python accumulation the values agree to accumulation-order
    rounding (integral counts — Accuracy hits, instance counts — are
    exact below 2**24; see docs/how_to/perf.md).
    """

    def __init__(self, base):
        base = base if isinstance(base, EvalMetric) else create(base)
        if not device_capable(base):
            raise MXNetError("metric %r has no device path" % base.name)
        self._base = base
        self._leaves = _leaves_of(base)
        self._fns = {}
        self._acc = None
        self._acc_dev = None
        self.sync_count = 0  # observability: how often a read forced a sync
        super().__init__(base.name)

    @property
    def base(self):
        return self._base

    # the documented EvalMetric attribute surface keeps working on the
    # wrapper (fit hands it to BatchEndParam callbacks): reads sync the
    # device accumulator into the base first, exactly like get()
    @property
    def num_inst(self):
        self._sync()
        return self._base.num_inst

    @property
    def sum_metric(self):
        self._sync()
        return self._base.sum_metric

    def reset(self):
        base = getattr(self, "_base", None)
        if base is None:  # EvalMetric.__init__ calls reset() pre-attrs
            return
        base.reset()
        self._acc = None

    def update(self, labels, preds, skip=None):
        """Accumulate one batch.  ``skip`` (an optional device bool
        scalar, e.g. the executor's in-graph NaN-guard batch flag) zeroes
        the batch's statistics inside the jit — exact skip-batch metric
        semantics with no host read."""
        import jax
        import jax.numpy as jnp

        labels_j = [_device_raw(x) for x in (labels or [])]
        preds_j = [_device_raw(x) for x in (preds or [])]
        # host-resident pieces (iterator labels, bulk-path numpy) join the
        # device-resident ones (module outputs / bound labels) on the
        # latter's device
        dev = None
        for v in preds_j + labels_j:
            devs = getattr(v, "devices", None)
            if callable(devs):
                ds = devs()
                if len(ds) == 1:
                    dev = next(iter(ds))
                    break

        def _place(v):
            if isinstance(v, _np.ndarray):
                return jax.device_put(v, dev) if dev is not None \
                    else jnp.asarray(v)
            return v

        labels_j = [_place(v) for v in labels_j]
        preds_j = [_place(v) for v in preds_j]
        key = (tuple((tuple(v.shape), str(v.dtype)) for v in labels_j),
               tuple((tuple(v.shape), str(v.dtype)) for v in preds_j),
               skip is not None)
        fn = self._fns.get(key)
        if fn is None:
            leaves = self._leaves
            gated = skip is not None

            def step(acc, labels, preds, *skip_arg):
                rows = []
                for leaf in leaves:
                    s, n = leaf._device_batch_stats(labels, preds)
                    rows.append(jnp.stack([jnp.asarray(s, jnp.float32),
                                           jnp.asarray(n, jnp.float32)]))
                stats = jnp.stack(rows)
                if gated:
                    stats = jnp.where(skip_arg[0],
                                      jnp.zeros_like(stats), stats)
                return acc + stats

            fn = jax.jit(step, donate_argnums=(0,))
            self._fns[key] = fn
        if self._acc is None:
            zeros = _np.zeros((len(self._leaves), 2), _np.float32)
            self._acc = jax.device_put(zeros, dev) if dev is not None \
                else jnp.asarray(zeros)
            self._acc_dev = dev
        elif dev is not None and self._acc_dev is not None \
                and dev != self._acc_dev:
            # rebind moved the executor: device-to-device hop, no host trip
            self._acc = jax.device_put(self._acc, dev)
            self._acc_dev = dev
        self._acc = fn(self._acc, labels_j, preds_j) if skip is None \
            else fn(self._acc, labels_j, preds_j, skip)

    def _sync(self):
        """THE sync point: fold the device accumulator into the wrapped
        host leaves (one blocking transfer, telemetry ``sync`` phase)."""
        if self._acc is None:
            return
        from . import telemetry as _telemetry

        with _telemetry.phase("sync"):
            vals = _np.asarray(self._acc)  # host-sync: ok — the metric read IS the sync point
        self._acc = None
        self.sync_count += 1
        for leaf, (s, n) in zip(self._leaves, vals):
            leaf.sum_metric += float(s)
            leaf.num_inst += int(n)

    def get(self):
        self._sync()
        return self._base.get()

    def get_name_value(self):
        self._sync()
        return self._base.get_name_value()

    def get_state(self):
        # the sync folds any device-accumulated stats into the host
        # leaves first, so the captured state is complete — this is the
        # "drain the device-metric accumulator" step of a preemption
        self._sync()
        return self._base.get_state()

    def set_state(self, state):
        self._acc = None
        self._base.set_state(state)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """reference ``metric.py`` np() — wrap a numpy feval."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """reference ``metric.py`` create"""
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, **kwargs))
        return composite
    if metric in ("acc",):
        metric = "accuracy"
    if metric in ("ce",):
        metric = "crossentropy"
    # underscore spellings used throughout the reference examples
    metric = str(metric).lower()
    metric = {"top_k_accuracy": "topkaccuracy",
              "cross-entropy": "crossentropy"}.get(metric, metric)
    return registry.create(metric, **kwargs)
