"""Symbolic RNN cells (reference ``python/mxnet/rnn/rnn_cell.py``).

API parity: ``RNNParams``, ``BaseRNNCell`` (``__call__``, ``unroll``,
``begin_state``, ``state_shape``, ``unpack_weights``/``pack_weights``),
``RNNCell``/``LSTMCell``/``GRUCell``, ``FusedRNNCell`` (+``unfuse``),
``SequentialRNNCell``, ``BidirectionalCell``, ``DropoutCell``,
``ModifierCell``/``ZoneoutCell``.

TPU-native differences from the reference:

* ``FusedRNNCell`` maps to the ``RNN`` op in ``ops/rnn.py`` — a
  ``lax.scan`` recurrence with one whole-sequence MXU matmul per layer —
  instead of ``cudnnRNNForwardTraining``; its parameter blob layout is this
  framework's canonical ``[Wx, Wh, bx, bh]``-per-(layer, direction) order.
* ``begin_state()`` with no ``batch_size`` returns ``None`` — ``unroll``
  then derives batch-polymorphic zero states from the data symbol via the
  ``_rnn_begin_state`` op (the reference's ``shape=(0, H)`` deferred-shape
  trick has no analog in a traced functional graph).  Pass
  ``batch_size=N`` to get concrete zero symbols for manual stepping.

Gate orders (shared with the fused op): LSTM ``i, f, g, o``; GRU ``r, z, n``.
"""

from __future__ import annotations

import numpy as np

from .. import ndarray
from .. import symbol
from ..base import MXNetError
from ..ops.rnn import _GATES, _layer_param_slices, rnn_param_size

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell"]


class RNNParams(object):
    """Container holding one Variable per parameter, shared across steps."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Returns (inputs, axis): inputs as a list of step symbols when
    ``merge is False`` or a single (merged) symbol when ``merge is True``."""
    assert inputs is not None
    axis = layout.find("T")
    in_axis = (in_layout or layout).find("T")
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            if length is None:
                raise MXNetError("length must be given to split a merged "
                                 "input sequence")
            inputs = list(symbol.SliceChannel(
                inputs, axis=in_axis, num_outputs=length, squeeze_axis=1))
        elif axis != in_axis:
            inputs = symbol.SwapAxis(inputs, dim1=axis, dim2=in_axis)
    else:
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=axis)
    return inputs, axis


class BaseRNNCell(object):
    """Abstract RNN cell (reference ``rnn_cell.py:87``)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        """One step: (output_symbol, new_states)."""
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_shape(self):
        """List of state shapes; 0 marks the batch dimension."""
        raise NotImplementedError()

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, batch_size=None, **kwargs):
        """Initial states.  With ``batch_size`` → concrete zero symbols;
        without → ``None`` (unroll derives states from the data symbol)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        if batch_size is None and func is None:
            return None
        states = []
        for shape in self.state_shape:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is not None:
                states.append(func(name=name, shape=shape, **kwargs))
                continue
            full = tuple(batch_size if s == 0 else s for s in shape)
            states.append(getattr(symbol, "_zeros")(name=name, shape=full))
        return states

    def _derived_begin_state(self, data_sym, batch_axis=0):
        """States derived from a data symbol via ``_rnn_begin_state``."""
        states = []
        for shape in self.state_shape:
            self._init_counter += 1
            states.append(getattr(symbol, "_rnn_begin_state")(
                data_sym, shape=shape, batch_axis=batch_axis,
                name="%sbegin_state_%d" % (self._prefix, self._init_counter)))
        return states

    def unpack_weights(self, args):
        """args dict with fused blobs -> dict with per-cell matrices.
        Plain cells already store per-cell matrices — identity copy."""
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell ``length`` steps (reference ``rnn_cell.py:245``).

        Returns (outputs, final_states); outputs merged into one symbol
        when ``merge_outputs=True``, else a list of per-step symbols.

        TPU note: gated cells hoist the input-side projection out of the
        unrolled recurrence — all ``length`` steps' ``x @ W_i2h`` run as
        ONE ``(T*N, I)`` matmul (MXU-sized) instead of T thin per-step
        matmuls; only the ``h @ W_h2h`` recurrence stays per-step.  Same
        weights, same math, same node-name scheme for the recurrent
        part — just a graph shape the MXU can actually fill (the
        unfused analog of what ``FusedRNNCell``/``ops/rnn.py`` do
        inside ``lax.scan``).
        """
        self.reset()
        inputs_list, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._derived_begin_state(inputs_list[0])
        states = begin_state
        i2h_seq = self._hoisted_i2h(inputs_list)
        outputs = []
        for i in range(length):
            if i2h_seq is None:
                output, states = self(inputs_list[i], states)
            else:
                self._counter += 1
                name = "%st%d_" % (self._prefix, self._counter)
                output, states = self._step(i2h_seq[i], states, name)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def _hoisted_i2h(self, inputs_list):
        """Per-step input projections from one whole-sequence matmul, or
        None when the cell doesn't support hoisting (then ``unroll``
        falls back to stepping ``self(...)``)."""
        return None

    def _i2h_seq(self, inputs_list, num_hidden_total):
        """Concat T step inputs on the batch axis, project once, slice
        back into per-step ``(N, G*H)`` blocks.  Callers guard the
        single-step case (hoisting one step is a no-op)."""
        cat = symbol.Concat(*inputs_list, dim=0,
                            name="%si2h_cat" % self._prefix)
        proj = symbol.FullyConnected(
            data=cat, weight=self._iW, bias=self._iB,
            num_hidden=num_hidden_total, name="%si2h_seq" % self._prefix)
        return list(symbol.SliceChannel(
            proj, num_outputs=len(inputs_list), axis=0,
            name="%si2h_split" % self._prefix))

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell: h' = act(W_x x + b_x + W_h h + b_h)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        return self._step(i2h, states, name)

    def _hoisted_i2h(self, inputs_list):
        if len(inputs_list) < 2:
            return None
        return self._i2h_seq(inputs_list, self._num_hidden)

    def _step(self, i2h, states, name):
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell; gate order ``i, f, g, o`` (shared with the fused RNN op)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias

        self._iB = self.params.get(
            "i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden), (0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        return self._step(i2h, states, name)

    def _hoisted_i2h(self, inputs_list):
        if len(inputs_list) < 2:
            return None
        return self._i2h_seq(inputs_list, self._num_hidden * 4)

    def _step(self, i2h, states, name):
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        sliced = symbol.SliceChannel(gates, num_outputs=4, axis=1,
                                     name="%sslice" % name)
        in_gate = symbol.Activation(sliced[0], act_type="sigmoid")
        forget_gate = symbol.Activation(sliced[1], act_type="sigmoid")
        in_transform = symbol.Activation(sliced[2], act_type="tanh")
        out_gate = symbol.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh",
                                              name="%sout" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell; gate order ``r, z, n``; the candidate uses
    ``r * (W_h h + b_h)`` like the fused op (cuDNN-style)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        return self._step(i2h, states, name)

    def _hoisted_i2h(self, inputs_list):
        if len(inputs_list) < 2:
            return None
        return self._i2h_seq(inputs_list, self._num_hidden * 3)

    def _step(self, i2h, states, name):
        prev_h = states[0]
        h2h = symbol.FullyConnected(data=prev_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h_n = symbol.SliceChannel(
            i2h, num_outputs=3, axis=1, name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h_n = symbol.SliceChannel(
            h2h, num_outputs=3, axis=1, name="%sh2h_slice" % name)
        reset = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h_n + reset * h2h_n,
                                       act_type="tanh")
        ones = next_h_tmp * 0.0 + 1.0
        next_h = (ones - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN backed by the ``RNN`` op (cuDNN-RNN analog)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        from ..initializer import FusedRNN as _FusedRNNInit
        self._parameter = self.params.get(
            "parameters", init=_FusedRNNInit(None, num_hidden, num_layers,
                                             mode, bidirectional,
                                             forget_bias))
        self._directions = 2 if bidirectional else 1

    @property
    def state_shape(self):
        n = self._num_layers * self._directions
        h = self._num_hidden
        if self._mode == "lstm":
            return [(n, 0, h), (n, 0, h)]
        return [(n, 0, h)]

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    @property
    def _num_gates(self):
        return _GATES[self._mode]

    def _blob_layout(self, input_size):
        return _layer_param_slices(input_size, self._num_hidden,
                                   self._num_layers, self._mode,
                                   self._bidirectional)

    def unpack_weights(self, args):
        """Split ``<prefix>parameters`` into per-layer i2h/h2h weights."""
        args = dict(args)
        blob = args.pop(self._prefix + "parameters")
        arr = blob.asnumpy() if isinstance(blob, ndarray.NDArray) else \
            np.asarray(blob)
        h = self._num_hidden
        input_size = self._infer_input_size(arr)
        for layer, direction, sl in self._blob_layout(input_size):
            pre = "%s%s%d_" % (self._prefix, "lr"[direction], layer)
            for key, nm in (("wx", "i2h_weight"), ("wh", "h2h_weight"),
                            ("bx", "i2h_bias"), ("bh", "h2h_bias")):
                off, shape = sl[key]
                n = int(np.prod(shape))
                args[pre + nm] = ndarray.array(
                    arr[off:off + n].reshape(shape))
        return args

    def pack_weights(self, args):
        args = dict(args)
        h = self._num_hidden
        first = args["%sl0_i2h_weight" % self._prefix]
        input_size = first.shape[1]
        total = rnn_param_size(input_size, h, self._num_layers, self._mode,
                               self._bidirectional)
        arr = np.zeros((total,), dtype=np.float32)
        for layer, direction, sl in self._blob_layout(input_size):
            pre = "%s%s%d_" % (self._prefix, "lr"[direction], layer)
            for key, nm in (("wx", "i2h_weight"), ("wh", "h2h_weight"),
                            ("bx", "i2h_bias"), ("bh", "h2h_bias")):
                off, shape = sl[key]
                n = int(np.prod(shape))
                w = args.pop(pre + nm)
                w = w.asnumpy() if isinstance(w, ndarray.NDArray) else \
                    np.asarray(w)
                arr[off:off + n] = w.reshape(-1)
        args[self._prefix + "parameters"] = ndarray.array(arr)
        return args

    def _infer_input_size(self, arr):
        """Solve blob length for input_size (layer-0 width)."""
        g, h = self._num_gates, self._num_hidden
        d = self._directions
        rest = rnn_param_size(1, h, self._num_layers, self._mode,
                              self._bidirectional) - d * g * h
        return (arr.size - rest) // (d * g * h)

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped — use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, True,
                                        in_layout=layout)
        if layout == "NTC":  # RNN op is time-major
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self._derived_begin_state(inputs, batch_axis=1)
        states = begin_state
        state_kw = {"state": states[0]}
        if self._mode == "lstm":
            state_kw["state_cell"] = states[1]
        rnn = getattr(symbol, "RNN")(
            data=inputs, parameters=self._parameter,
            state_size=self._num_hidden, num_layers=self._num_layers,
            bidirectional=self._bidirectional, p=self._dropout,
            state_outputs=self._get_next_state, mode=self._mode,
            name=self._prefix + "rnn", **state_kw)
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if layout == "NTC":
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs, in_layout=layout)
        return outputs, states

    def unfuse(self):
        """Equivalent stack of unfused cells sharing this blob's layout."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden,
                                       forget_bias=self._forget_bias,
                                       prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_"
                                      % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Sequentially stacked cells (reference ``rnn_cell.py:673``)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_shape(self):
        return sum([c.state_shape for c in self._cells], [])

    def begin_state(self, func=None, batch_size=None, **kwargs):
        assert not self._modified
        if batch_size is None and func is None:
            return None
        return sum([c.begin_state(func=func, batch_size=batch_size,
                                  **kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_shape)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            first, _ = _normalize_sequence(length, inputs, layout, False)
            begin_state = self._derived_begin_state_seq(first[0])
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_shape)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states

    def _derived_begin_state_seq(self, data_sym):
        states = []
        for cell in self._cells:
            states.extend(cell._derived_begin_state(data_sym))
        return states


class DropoutCell(BaseRNNCell):
    """Applies dropout on the input (reference ``rnn_cell.py:749``)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_shape(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if isinstance(inputs, symbol.Symbol):
            return self(inputs, [])
        return [self(i, [])[0] for i in inputs], []


class ModifierCell(BaseRNNCell):
    """Base for cells that wrap another cell (reference ``rnn_cell.py:783``)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_shape(self):
        return self.base_cell.state_shape

    def begin_state(self, func=None, batch_size=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, batch_size=batch_size,
                                           **kwargs)
        self.base_cell._modified = True
        return begin

    def _derived_begin_state(self, data_sym, batch_axis=0):
        return self.base_cell._derived_begin_state(data_sym, batch_axis)

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout regularization: keep previous output/state with prob p."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell does not support zoneout; unfuse() first"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            # Dropout(ones, p) is 1/(1-p) w.p. (1-p) → scale to a 0/1 mask
            return symbol.Dropout(symbol.ones_like(like), p=p) * (1.0 - p)

        prev_output = self.prev_output if self.prev_output is not None \
            else next_output * 0.0
        output = next_output
        if p_outputs != 0.0:
            m = mask(p_outputs, next_output)
            output = m * next_output + (1.0 - m) * prev_output
        if p_states != 0.0:
            new_states = []
            for new_s, old_s in zip(next_states, states):
                m = mask(p_states, new_s)
                new_states.append(m * new_s + (1.0 - m) * old_s)
        else:
            new_states = next_states
        self.prev_output = output
        return output, new_states


class BidirectionalCell(BaseRNNCell):
    """Runs l_cell forward and r_cell on the reversed sequence, concats."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped — use unroll")

    @property
    def state_shape(self):
        return sum([c.state_shape for c in self._cells], [])

    def begin_state(self, func=None, batch_size=None, **kwargs):
        assert not self._modified
        if batch_size is None and func is None:
            return None
        return sum([c.begin_state(func=func, batch_size=batch_size,
                                  **kwargs) for c in self._cells], [])

    def _derived_begin_state(self, data_sym, batch_axis=0):
        states = []
        for c in self._cells:
            states.extend(c._derived_begin_state(data_sym, batch_axis))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs_list, axis = _normalize_sequence(length, inputs, layout,
                                                False)
        if begin_state is None:
            begin_state = self._derived_begin_state(inputs_list[0])
        states = begin_state
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_shape)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs_list, begin_state=states[:n_l],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs_list)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False)
        outputs = [
            symbol.Concat(l_o, r_o, dim=1,
                          name="%st%d" % (self._output_prefix, i))
            for i, (l_o, r_o) in enumerate(zip(l_outputs,
                                               reversed(r_outputs)))]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, l_states + r_states


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args
