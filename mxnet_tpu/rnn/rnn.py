"""RNN checkpoint helpers (``mx.rnn.save_rnn_checkpoint`` et al.).

Reference: ``python/mxnet/rnn/rnn.py:15-108`` — fused cells store their
parameters as one packed blob per layer/direction, so checkpoints written
from a fused-cell module must be unpacked into per-gate arrays before
saving (portable across fused/unfused graphs) and re-packed after loading.
"""

from ..model import load_checkpoint, save_checkpoint
from .rnn_cell import BaseRNNCell

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def _as_cell_list(cells):
    if isinstance(cells, BaseRNNCell):
        return [cells]
    return list(cells)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Unpack every cell's fused blobs in ``arg_params`` then write the
    standard ``prefix-symbol.json`` + ``prefix-%04d.params`` pair
    (reference ``rnn/rnn.py:15``)."""
    for cell in _as_cell_list(cells):
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load a checkpoint and re-pack per-gate arrays into each cell's fused
    blob layout (reference ``rnn/rnn.py:45``).  Returns
    ``(symbol, arg_params, aux_params)``."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _as_cell_list(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback writing unpacked checkpoints every ``period``
    epochs (reference ``rnn/rnn.py:80``)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
