"""Bucketing data iterator for variable-length sequences.

Reference: ``python/mxnet/rnn/io.py`` (``encode_sentences`` :13,
``BucketSentenceIter`` :61) — the data side of the PTB LM baseline
(SURVEY §2.9 config 3).  Each batch carries a ``bucket_key`` so
``BucketingModule`` can pick (or trace+compile) the executor for that
sequence length.
"""

from __future__ import annotations

import bisect
import logging
import random

import numpy as np

from .. import ndarray
from ..io import DataBatch, DataIter, DataDesc

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Encode tokenized sentences into integer id lists, building (or
    extending) ``vocab``.  Returns (encoded, vocab)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                assert new_vocab, "Unknown token %s" % word
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed LM iterator: pads each sentence up to its bucket length;
    label is the input shifted one step left (next-token prediction)."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NTC"):
        super().__init__(batch_size)
        if not buckets:
            counts = np.bincount([len(s) for s in sentences])
            buckets = [i for i, j in enumerate(counts) if j >= batch_size]
        buckets = sorted(buckets)

        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        # an empty bucket would collapse to a 1-D (0,) array and break the
        # label shift in reset(); keep every bucket 2-D
        self.data = [np.asarray(b, dtype=dtype).reshape(-1, n)
                     for b, n in zip(self.data, buckets)]
        if ndiscard:
            logging.warning("discarded %d sentences longer than the largest "
                            "bucket.", ndiscard)

        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.major_axis = layout.find("N")
        self.layout = layout
        self.default_bucket_key = max(buckets)

        if self.major_axis == 0:
            shape = (batch_size, self.default_bucket_key)
        elif self.major_axis == 1:
            shape = (self.default_bucket_key, batch_size)
        else:
            raise ValueError("invalid layout %s" % layout)
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend(
                (i, j) for j in range(0, len(buck) - batch_size + 1,
                                      batch_size))
        self.curr_idx = 0
        self.nddata = []
        self.ndlabel = []
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(ndarray.array(buck, dtype=self.dtype))
            self.ndlabel.append(ndarray.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        if self.major_axis == 1:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        else:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape,
                                    layout=self.layout)])
