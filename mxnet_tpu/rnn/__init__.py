"""RNN toolkit (``mx.rnn``) — reference ``python/mxnet/rnn/``.

Symbolic RNN cells plus the fused multi-layer cell backed by the TPU-native
``RNN`` op (``ops/rnn.py``), and the bucketing data iterator.
"""

from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell, FusedRNNCell,
                       SequentialRNNCell, BidirectionalCell, DropoutCell,
                       ModifierCell, ZoneoutCell, RNNParams)
from .io import BucketSentenceIter, encode_sentences
from .rnn import (save_rnn_checkpoint, load_rnn_checkpoint,
                  do_rnn_checkpoint)

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell", "FusedRNNCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "RNNParams",
           "BucketSentenceIter", "encode_sentences",
           "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]
