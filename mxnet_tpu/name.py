"""Name manager (reference ``python/mxnet/name.py``) — re-export."""

from .base import NameManager, Prefix

__all__ = ["NameManager", "Prefix"]
