"""Device contexts: ``mx.cpu()`` / ``mx.tpu()`` (+ ``mx.gpu()`` alias).

Reference: ``python/mxnet/context.py:1-126`` — ``Context(device_type,
device_id)``, the with-scope ``current_context``. TPU-native twist (the
BASELINE.json north star): device_type 4 is ``tpu`` and maps onto a JAX/PJRT
device; ``gpu`` is kept as an accepted alias for the local accelerator so
reference training scripts run unmodified.

A Context is hashable/comparable by (device_type_string-normalised, id) so it
keys executor caches exactly like the reference's Context does.
"""

from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "tpu", "gpu", "current_context", "num_tpus", "num_gpus"]

# Accelerator device types all normalise to the local PJRT accelerator; this is
# what lets `--gpus 0` style reference scripts run on a TPU chip untouched.
_ACCEL_TYPES = ("tpu", "gpu")


class Context:
    """A device context. reference ``context.py:5-88``."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}
    _tls = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if isinstance(device_type, str):
                device_type = Context.devstr2type[device_type]
            self.device_typeid = device_type
            self.device_id = device_id

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self._norm_type() == other._norm_type()
            and self.device_id == other.device_id
        )

    def _norm_type(self):
        t = self.device_type
        return "accel" if t in _ACCEL_TYPES else "cpu"

    def __hash__(self):
        return hash((self._norm_type(), self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    # -- JAX mapping ------------------------------------------------------
    def jax_device(self):
        """The PJRT device backing this context.

        Contexts are PROCESS-LOCAL (a worker's ``mx.cpu(0)``/``mx.tpu(0)``
        is its own chip): under a ``jax.distributed`` process group the
        lookup uses addressable devices only — ``jax.devices()`` would
        enumerate every process's chips."""
        if self._norm_type() == "cpu":
            devs = jax.local_devices(backend="cpu") \
                if jax.default_backend() != "cpu" else jax.local_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        devs = _accel_devices()
        if not devs:
            raise RuntimeError(
                "Context %r: no accelerator (TPU) devices visible to JAX" % (self,)
            )
        if self.device_id >= len(devs):
            raise ValueError(
                "Context %r: only %d accelerator device(s) present" % (self, len(devs))
            )
        return devs[self.device_id]

    def __enter__(self):
        if not hasattr(Context._tls, "stack"):
            Context._tls.stack = [Context(_default_typeid(), 0)]
        Context._tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._tls.stack.pop()

    @staticmethod
    def current():
        if not hasattr(Context._tls, "stack"):
            Context._tls.stack = [Context(_default_typeid(), 0)]
        return Context._tls.stack[-1]


def _accel_devices():
    """Process-local non-CPU PJRT devices (TPU chips; axon tunnel chip
    included)."""
    if jax.default_backend() == "cpu":
        return []
    return [d for d in jax.local_devices() if d.platform != "cpu"]


def _default_typeid():
    return 4 if _accel_devices() else 1


def cpu(device_id=0):
    """reference ``context.py:90``"""
    return Context(1, device_id)


def gpu(device_id=0):
    """Alias for the local accelerator — keeps reference scripts runnable."""
    return Context(2, device_id)


def tpu(device_id=0):
    """The new first-class device type (BASELINE.json north star)."""
    return Context(4, device_id)


def num_tpus():
    return len(_accel_devices())


num_gpus = num_tpus


def current_context():
    """reference ``context.py:122``"""
    return Context.current()
