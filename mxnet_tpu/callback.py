"""Training callbacks: checkpointing, metric logging, throughput.

API parity with the reference's ``python/mxnet/callback.py`` (same
callables, same ``BatchEndParam``-shaped argument contract), built on
this repo's conventions: throughput is measured between explicit *marks*
(the last log point) with a monotonic clock, so the reported samples/sec
stays correct even when the callback list drops or duplicates batch
events — the reference instead assumes exactly ``frequent`` batches
elapsed between logs.

Batch-end callbacks receive any object with ``epoch``, ``nbatch`` and
``eval_metric`` attributes (``model.BatchEndParam``); epoch-end
callbacks receive ``(epoch, symbol, arg_params, aux_params)``.
"""

from __future__ import annotations

import logging
import sys
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback: ``mod.save_checkpoint`` every ``period``
    epochs (reference ``callback.py:11``)."""
    period = max(1, int(period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback: save symbol+params every ``period`` epochs
    (reference ``callback.py:39``)."""
    from .model import save_checkpoint

    period = max(1, int(period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback: log the running training metric every
    ``period`` batches (reference ``callback.py`` log_train_metric)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Batch-end callback: log samples/sec every ``frequent`` batches
    (reference ``callback.py:89``).

    Throughput is ``(batches since the last log) * batch_size /
    elapsed`` from a monotonic clock — measured, not assumed, so a
    missed callback or an epoch boundary can't skew the rate.  A drop in
    ``nbatch`` (new epoch / iterator reset) re-arms the mark without
    logging a bogus first interval.
    """

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._mark = None  # (nbatch, perf_counter) at the last log/reset

    def __call__(self, param):
        now = time.perf_counter()
        count = param.nbatch
        if self._mark is None or count < self._mark[0]:
            self._mark = (count, now)  # fresh epoch: arm, don't log
            return
        if count == self._mark[0] or count % self.frequent != 0:
            return
        elapsed = now - self._mark[1]
        speed = (count - self._mark[0]) * self.batch_size / max(elapsed, 1e-9)
        self._mark = (count, now)
        if param.eval_metric is not None:
            metrics = "".join("\tTrain-%s=%f" % nv
                              for nv in param.eval_metric.get_name_value())
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                         param.epoch, count, speed, metrics)
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, count, speed)


class ProgressBar:
    """Batch-end callback: in-place text progress bar over ``total``
    batches (reference ``callback.py`` ProgressBar)."""

    def __init__(self, total, length=80):
        self.total = total
        self.length = length

    def __call__(self, param):
        frac = min(max(param.nbatch / float(self.total), 0.0), 1.0)
        filled = round(self.length * frac)
        bar = "=" * filled + "-" * (self.length - filled)
        sys.stdout.write("[%s] %d%%\r" % (bar, int(frac * 100 + 0.999999)))
