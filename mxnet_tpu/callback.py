"""Training callbacks: checkpointing, metric logging, throughput.

API parity with the reference's ``python/mxnet/callback.py`` (same
callables, same ``BatchEndParam``-shaped argument contract), built on
this repo's conventions: throughput is measured between explicit *marks*
(the last log point) with a monotonic clock, so the reported samples/sec
stays correct even when the callback list drops or duplicates batch
events — the reference instead assumes exactly ``frequent`` batches
elapsed between logs.

Batch-end callbacks receive any object with ``epoch``, ``nbatch`` and
``eval_metric`` attributes (``model.BatchEndParam``); epoch-end
callbacks receive ``(epoch, symbol, arg_params, aux_params)``.
"""

from __future__ import annotations

import logging
import sys
import time

from . import perfdebug as _perfdebug
from . import telemetry as _telemetry

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "TelemetryReport"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback: ``mod.save_checkpoint`` every ``period``
    epochs (reference ``callback.py:11``)."""
    period = max(1, int(period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback: save symbol+params every ``period`` epochs
    (reference ``callback.py:39``)."""
    from .model import save_checkpoint

    period = max(1, int(period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback: log the running training metric every
    ``period`` batches (reference ``callback.py`` log_train_metric)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Batch-end callback: log samples/sec every ``frequent`` batches
    (reference ``callback.py:89``).

    Throughput is ``(batches since the last log) * batch_size /
    elapsed`` from a monotonic clock — measured, not assumed, so a
    missed callback or an epoch boundary can't skew the rate.  A drop in
    ``nbatch`` (new epoch / iterator reset) re-arms the mark without
    logging a bogus first interval.

    Besides the instantaneous rate, an exponentially-smoothed rate
    (``smoothing`` is the weight kept on history per log interval) is
    reported — the number to read on jittery input pipelines — and both
    survive in ``telemetry.snapshot()`` as the
    ``fit.samples_per_sec{kind=instant|smoothed}`` gauges instead of
    scrolling away on stdout.

    Device-metric discipline: the metric is read (``get_name_value``)
    ONLY inside the ``frequent``-cadence log branch — with a
    :class:`~mxnet_tpu.metric.DeviceMetric` that read is the sync point,
    so rate reporting never forces a per-batch device sync.
    ``auto_reset=True`` (reference parity) additionally resets the metric
    after each log, making the printed values per-interval rather than
    running; the default ``False`` keeps the running-epoch semantics.
    """

    def __init__(self, batch_size, frequent=50, smoothing=0.7,
                 auto_reset=False):
        self.batch_size = batch_size
        self.frequent = frequent
        self.smoothing = min(max(float(smoothing), 0.0), 1.0)
        self.auto_reset = auto_reset
        self._mark = None  # (nbatch, perf_counter) at the last log/reset
        self._ema = None

    def __call__(self, param):
        now = time.perf_counter()
        count = param.nbatch
        if self._mark is None or count < self._mark[0]:
            self._mark = (count, now)  # fresh epoch: arm, don't log
            return
        if count == self._mark[0] or count % self.frequent != 0:
            return
        elapsed = now - self._mark[1]
        speed = (count - self._mark[0]) * self.batch_size / max(elapsed, 1e-9)
        self._mark = (count, now)
        self._ema = speed if self._ema is None else \
            self.smoothing * self._ema + (1.0 - self.smoothing) * speed
        if _telemetry.enabled():
            _telemetry.set_gauge("fit.samples_per_sec", speed,
                                 kind="instant")
            _telemetry.set_gauge("fit.samples_per_sec", self._ema,
                                 kind="smoothed")
        # live MFU: the rate is already measured, so folding it against
        # the captured step flops (perfdebug attribution) and the chip's
        # rated peak costs no extra sync; None when either is unknown
        mfu = _perfdebug.note_throughput(self._ema, self.batch_size)
        mfu_txt = "" if mfu is None else " MFU %.1f%%" % mfu
        if param.eval_metric is not None:
            metrics = "".join("\tTrain-%s=%f" % nv
                              for nv in param.eval_metric.get_name_value())
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec "
                         "(smoothed %.2f)%s%s",
                         param.epoch, count, speed, self._ema, mfu_txt,
                         metrics)
            if self.auto_reset:
                param.eval_metric.reset()
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec "
                         "(smoothed %.2f)%s",
                         param.epoch, count, speed, self._ema, mfu_txt)


class ProgressBar:
    """Batch-end callback: in-place text progress bar over ``total``
    batches (reference ``callback.py`` ProgressBar).

    When ``nbatch`` reaches ``total`` a terminating newline is emitted
    (once per fill) so the cursor does not stay parked on the bar line;
    an ``nbatch`` drop (next epoch) re-arms the bar.  ``length`` and
    ``total`` are clamped to >= 1 (an unknown batch count must not
    divide by zero inside the fit loop's callback).
    """

    def __init__(self, total, length=80):
        self.total = max(1, int(total))
        self.length = max(1, int(length))
        self._done = False
        self._last = None

    def __call__(self, param):
        if self._last is not None and param.nbatch < self._last:
            self._done = False  # new epoch: the bar restarts
        self._last = param.nbatch
        frac = min(max(param.nbatch / float(self.total), 0.0), 1.0)
        filled = round(self.length * frac)
        bar = "=" * filled + "-" * (self.length - filled)
        sys.stdout.write("[%s] %d%%\r" % (bar, int(frac * 100 + 0.999999)))
        if param.nbatch >= self.total and not self._done:
            sys.stdout.write("\n")
            self._done = True


class TelemetryReport:
    """Structured training report from the telemetry registry — the
    replacement for eyeballing Speedometer lines (docs/observability.md).

    Use one instance as BOTH callbacks::

        report = mx.callback.TelemetryReport(frequent=50)
        mod.fit(train, batch_end_callback=report,
                epoch_end_callback=report.epoch, ...)

    Every ``frequent`` batches it logs the per-phase step-time breakdown
    (ms/batch of data wait, forward+backward, optimizer/kvstore sync,
    metric — deltas since its last report, not lifetime averages) plus
    transport and compile counter deltas.  At epoch end it samples
    device/host memory, logs the epoch summary and, with ``dump_path``
    set, rewrites the snapshot JSON there.  A no-op (with one hint log)
    while telemetry is disabled.
    """

    _PHASES = ("data", "forward_backward", "update", "metric", "sync",
               "bulk_step", "checkpoint")
    _COUNTERS = ("kvstore.push.count", "kvstore.pull.count",
                 "kvstore.reconnects", "xla.compile.count",
                 "resilience.nan_batches", "resilience.recordio_skipped")

    def __init__(self, frequent=50, logger=None, dump_path=None):
        self.frequent = max(1, int(frequent))
        self.logger = logger or logging.getLogger(__name__)
        self.dump_path = dump_path
        self._base = None  # (phase_totals, counter totals) at last report
        self._hinted = False

    def _delta(self):
        phases = _telemetry.phase_totals("fit")
        counters = {c: _telemetry.counter_total(c) for c in self._COUNTERS}
        base = self._base or ({}, {c: 0 for c in self._COUNTERS})
        self._base = (phases, counters)
        dp = {}
        for ph, (s, n) in phases.items():
            s0, n0 = base[0].get(ph, (0.0, 0))
            if n > n0:
                dp[ph] = (s - s0, n - n0)
        dc = {c: counters[c] - base[1].get(c, 0) for c in self._COUNTERS}
        return dp, dc

    def __call__(self, param):
        if not _telemetry.enabled():
            if not self._hinted:
                self._hinted = True
                self.logger.info(
                    "TelemetryReport: telemetry is disabled — set "
                    "MXNET_TELEMETRY=1 (or mx.telemetry.enable()) for "
                    "per-phase reports")
            return
        if param.nbatch == 0 or param.nbatch % self.frequent != 0:
            return
        dp, dc = self._delta()
        phase_txt = "  ".join(
            "%s %.1fms" % (ph, 1e3 * s / n)
            for ph, (s, n) in sorted(dp.items(),
                                     key=lambda kv: -kv[1][0]))
        counter_txt = "  ".join("%s +%d" % (c.split(".", 1)[1], d)
                                for c, d in sorted(dc.items()) if d)
        self.logger.info("Epoch[%d] Batch[%d] phases/batch: %s%s",
                         param.epoch, param.nbatch,
                         phase_txt or "(no phase data)",
                         ("  |  " + counter_txt) if counter_txt else "")

    def epoch(self, epoch, sym=None, arg=None, aux=None):
        """Epoch-end half of the callback pair."""
        if not _telemetry.enabled():
            return
        _telemetry.sample_memory()
        totals = _telemetry.phase_totals("fit")
        txt = "  ".join("%s %.2fs/%d" % (ph, s, n)
                        for ph, (s, n) in sorted(totals.items(),
                                                 key=lambda kv: -kv[1][0]))
        rss = _telemetry.gauge_value("memory.host.max_rss_bytes")
        extras = []
        if rss and rss > 0:
            extras.append("host max RSS %.0f MB" % (rss / 1e6))
        mfu = _telemetry.gauge_value("perf.mfu_pct")
        if mfu is not None:
            extras.append("MFU %.1f%%" % mfu)
        hbm = _telemetry.gauge_value("perf.hbm_peak_bytes")
        if hbm:
            extras.append("HBM peak %.0f MB" % (hbm / 1e6))
        self.logger.info(
            "Epoch[%d] telemetry: %s%s", epoch, txt or "(no phase data)",
            ("  |  " + "  ".join(extras)) if extras else "")
        if self.dump_path:
            _telemetry.dump(self.dump_path)
