"""Optimizers (``mx.optimizer``).

Reference: ``python/mxnet/optimizer.py`` (SURVEY §2.6): registry, Optimizer
base with lr/wd multipliers and num_update-driven scheduling, SGD/DCASGD/NAG/
SGLD/ccSGD/Adam/AdaGrad/RMSProp/AdaDelta/Test, and ``get_updater`` (the
closure applied per device or on the PS server).

TPU design: each ``update`` call dispatches a fused XLA kernel via the
``*_update`` ops (``ops/optimizer_op.py``); the Module fast path fuses the
whole multi-tensor update into the jitted train step (``module/module.py``),
which is the analog of the reference's update-on-kvstore fusion.
"""

from __future__ import annotations

import math

import numpy as np

from .base import MXNetError, Registry
from .ndarray import NDArray, zeros
from . import ndarray as nd
from .random import normal as _random_normal

__all__ = ["Optimizer", "SGD", "DCASGD", "NAG", "SGLD", "ccSGD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Test", "create", "register",
           "get_updater", "Updater"]

registry = Registry("optimizer")
register = registry.register


class Optimizer:
    """reference ``optimizer.py:25``"""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym

    # -- serialization to "kvstore servers" (reference pickles the optimizer
    # to PS servers, python/mxnet/kvstore.py:232) -------------------------
    def __getstate__(self):
        # drop the symbol: it holds OpDef closures that can't (and needn't)
        # travel to a kvstore server.  Behavior-preserving: sym is only read
        # inside explicit set_lr_mult/set_wd_mult calls, never by
        # _get_lr/_get_wd, so a pickled copy computes identical updates.
        d = dict(self.__dict__)
        d["sym"] = None
        return d

    def dumps(self):
        import pickle

        return pickle.dumps(self)

    @staticmethod
    def loads(buf):
        import pickle

        return pickle.loads(buf)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    # -- lr/wd multipliers (reference optimizer.py set_lr_mult etc.) ------
    def set_lr_scale(self, args_lrscale):  # deprecated reference API
        self.lr_mult = {self.idx2name.get(i, i): s
                        for i, s in args_lrscale.items()}

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _clip(self):
        return self.clip_gradient if self.clip_gradient is not None else -1.0


@register
class SGD(Optimizer):
    """reference ``optimizer.py:279`` — fused sgd_update/sgd_mom_update."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is not None:
            new_w, new_m = nd.sgd_mom_update(
                weight, grad, state, lr=lr, wd=wd, momentum=self.momentum,
                rescale_grad=self.rescale_grad, clip_gradient=self._clip())
            weight._jx = new_w._jx
            state._jx = new_m._jx
        else:
            nd.sgd_update(weight, grad, lr=lr, wd=wd,
                          rescale_grad=self.rescale_grad,
                          clip_gradient=self._clip(), out=weight)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference ``optimizer.py:380``)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad += wd * weight
            mom += grad
            grad += self.momentum * mom
            weight += -lr * grad
        else:
            weight += -lr * (grad + wd * weight)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference ``optimizer.py:325``)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        mon, previous_weight = state
        delay = grad + self.lamda * grad * grad * (weight - previous_weight)
        if mon is not None:
            mon *= self.momentum
            mon += -lr * (delay + wd * weight)
        else:
            mon = -lr * (delay + wd * weight)
        weight.copyto(previous_weight)
        weight += mon


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference ``optimizer.py:416``)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        noise = _random_normal(0, math.sqrt(lr), weight.shape,
                               weight.context)
        weight += (-lr / 2) * (grad + wd * weight) + noise


@register
class ccSGD(SGD):
    """Kept for API parity (reference ``optimizer.py:445`` — C-side SGD)."""


@register
class Adam(Optimizer):
    """reference ``optimizer.py:451`` — fused adam_update, with the
    reference's bias-corrected effective lr."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        new_w, new_mean, new_var = nd.adam_update(
            weight, grad, mean, var, lr=lr, wd=wd, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon,
            rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        weight._jx = new_w._jx
        mean._jx = new_mean._jx
        var._jx = new_var._jx


@register
class AdaGrad(Optimizer):
    """reference ``optimizer.py:499``"""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        history = state
        history += grad * grad
        weight += -lr * (grad / nd.sqrt(history + self.float_stable_eps)
                         + wd * weight)


@register
class RMSProp(Optimizer):
    """reference ``optimizer.py:536`` — centered=False → Hinton's rmsprop
    (fused rmsprop_update); centered=True → Graves 2013 (rmspropalex)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context))
        return (zeros(weight.shape, weight.context),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        cw = self.clip_weights if self.clip_weights is not None else -1.0
        if not self.centered:
            (n,) = state
            new_w, new_n = nd.rmsprop_update(
                weight, grad, n, lr=lr, wd=wd, gamma1=self.gamma1,
                epsilon=self.epsilon, rescale_grad=self.rescale_grad,
                clip_gradient=self._clip(), clip_weights=cw)
            weight._jx, n._jx = new_w._jx, new_n._jx
        else:
            n, g, delta = state
            new_w, new_n, new_g, new_d = nd.rmspropalex_update(
                weight, grad, n, g, delta, lr=lr, wd=wd, gamma1=self.gamma1,
                gamma2=self.gamma2, epsilon=self.epsilon,
                rescale_grad=self.rescale_grad, clip_gradient=self._clip(),
                clip_weights=cw)
            weight._jx, n._jx, g._jx, delta._jx = \
                new_w._jx, new_n._jx, new_g._jx, new_d._jx


@register
class AdaDelta(Optimizer):
    """reference ``optimizer.py:605``"""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g *= self.rho
        acc_g += (1.0 - self.rho) * grad * grad
        current_delta = (nd.sqrt(acc_delta + self.epsilon)
                         / nd.sqrt(acc_g + self.epsilon)) * grad
        acc_delta *= self.rho
        acc_delta += (1.0 - self.rho) * current_delta * current_delta
        weight -= current_delta + wd * weight


@register
class Test(Optimizer):
    """reference ``optimizer.py:653``"""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


def create(name, **kwargs):
    """reference ``optimizer.py`` create_optimizer"""
    return registry.create(name, **kwargs)


class Updater:
    """reference ``optimizer.py`` get_updater closure, as a picklable class
    (kvstore servers receive it).

    Under a mesh module (``context=Mesh`` / ``fit(kvstore='mesh')``)
    the state arrays are *global jax Arrays* — replicated, or
    row-sharded over the batch axis for ZeRO-eligible params
    (``Module._place_opt_state``).  The serialization contract is
    sharding-agnostic: ``get_states`` pickles NDArrays, which gathers
    each to one full host buffer, so the bytes are identical to a
    single-device run's and a snapshot restores across any mesh shape
    (the module re-places them on its own mesh after ``set_states`` —
    unpickled arrays come back host-committed)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        import pickle

        self.states = pickle.loads(states)

    def get_states(self):
        import pickle

        return pickle.dumps(self.states)


def get_updater(optimizer):
    return Updater(optimizer)
