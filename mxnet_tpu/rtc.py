"""Runtime kernel authoring — the NVRTC analog for TPU.

Reference: ``src/common/mxrtc.cc`` + ``include/mxnet/mxrtc.h`` +
``python/mxnet/rtc.py``: ``Rtc(name, inputs, outputs, kernel)`` compiles a
CUDA source string via NVRTC (with a PTX cache keyed on source,
``mxrtc.cc:11-22``) and ``push(ins, outs, grid, block)`` launches it.

TPU-native: the "assembler" is XLA/Mosaic, so a runtime kernel is a Python
source string defining either a plain JAX function (lowered by XLA) or a
Pallas TPU kernel (lowered by Mosaic).  Compilation is cached on the source
hash exactly like the reference's PTX cache; ``push`` writes results into the
output NDArrays.
"""

from __future__ import annotations

import hashlib
import textwrap

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Rtc"]

_MODULE_CACHE = {}  # source-hash -> compiled python namespace (PTX cache analog)


def _compile(source):
    key = hashlib.sha1(source.encode()).hexdigest()
    if key not in _MODULE_CACHE:
        import jax
        import jax.numpy as jnp

        ns = {"jax": jax, "jnp": jnp, "np": __import__("numpy")}
        try:
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            ns["pl"] = pl
            ns["pltpu"] = pltpu
        except ImportError:  # pragma: no cover
            pass
        exec(compile(textwrap.dedent(source), "<mx.rtc>", "exec"), ns)
        _MODULE_CACHE[key] = ns
    return _MODULE_CACHE[key]


class Rtc:
    """Runtime-compiled kernel (reference ``python/mxnet/rtc.py:Rtc``).

    ``kernel`` is Python source that must define a function named ``name``
    taking ``len(inputs)`` arrays and returning ``len(outputs)`` arrays (one
    array may be returned bare).  The function may be a plain JAX function or
    construct/invoke a Pallas kernel; it is jitted once and cached.

    Example::

        rtc = mx.rtc.Rtc('axpy', ['x', 'y'], ['out'], '''
        def axpy(x, y):
            return 2.0 * x + y
        ''')
        rtc.push([x_nd, y_nd], [out_nd])
    """

    def __init__(self, name, inputs, outputs, kernel):
        import jax

        self.name = name
        self.input_names = list(inputs)
        self.output_names = list(outputs)
        ns = _compile(kernel)
        if name not in ns or not callable(ns[name]):
            raise MXNetError(
                "rtc kernel source must define function %r" % name)
        self._fn = jax.jit(ns[name])

    def push(self, ins, outs, grid_dims=None, block_dims=None):
        """Run the kernel; writes into ``outs`` NDArrays.  ``grid_dims``/
        ``block_dims`` accepted for reference API compatibility (the
        launch geometry is chosen by XLA/Mosaic here)."""
        del grid_dims, block_dims
        if len(ins) != len(self.input_names):
            raise MXNetError("rtc %s: expected %d inputs"
                             % (self.name, len(self.input_names)))
        res = self._fn(*[x._jx for x in ins])
        if not isinstance(res, (tuple, list)):
            res = [res]
        if len(res) != len(self.output_names):
            raise MXNetError("rtc %s: kernel returned %d outputs, declared %d"
                             % (self.name, len(res), len(self.output_names)))
        if len(outs) != len(self.output_names):
            raise MXNetError("rtc %s: expected %d output NDArrays, got %d"
                             % (self.name, len(self.output_names), len(outs)))
        for dst, src in zip(outs, res):
            if not isinstance(dst, NDArray):
                raise MXNetError("rtc outputs must be NDArrays")
            if tuple(src.shape) != tuple(dst._jx.shape):
                raise MXNetError(
                    "rtc %s: kernel output shape %s != output NDArray "
                    "shape %s" % (self.name, tuple(src.shape),
                                  tuple(dst._jx.shape)))
            dst._jx = src.astype(dst._jx.dtype) \
                if src.dtype != dst._jx.dtype else src
        return outs
