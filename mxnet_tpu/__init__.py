"""mxnet_tpu — a TPU-native deep-learning framework with the MXNet-0.9.5
capability surface (see SURVEY.md for the blueprint).

Import as ``import mxnet_tpu as mx`` — the namespaces mirror the reference's
``python/mxnet/__init__.py``: ``mx.nd``, ``mx.sym``, ``mx.mod``, ``mx.io``,
``mx.kv``, ``mx.optimizer``, ``mx.init``, ``mx.metric``, ``mx.rnn``, …
"""

__version__ = "0.1.0"

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_tpus

from . import telemetry
from . import perfdebug
from . import faults
from . import compile_cache
# MXNET_COMPILE_CACHE_DIR arms the persistent XLA compile cache before
# any executor build can compile (no-op when unset; never raises)
compile_cache._init_from_env()
from . import retry
from . import elastic

from . import ops
from . import ndarray
from . import ndarray as nd
from . import random
from . import random as rnd

from . import attribute
from .attribute import AttrScope
from . import name
from . import symbol
from . import symbol as sym
from .symbol import Symbol, Group, Variable
from . import executor
from .executor import Executor

from . import initializer
from . import initializer as init
from . import optimizer
from . import optimizer as opt
from .optimizer import Optimizer
from . import metric
from . import lr_scheduler
from . import callback
from . import monitor
from . import monitor as mon
from .monitor import Monitor

from . import io
from . import kvstore
from . import kvstore as kv
from .kvstore import KVStore

from . import rnn

from . import module
from . import module as mod
from .module import Module

from . import model
from .model import FeedForward
from . import checkpoint
from .checkpoint import TrainingPreempted
from . import models

from . import log
from . import operator
from . import predict
from . import serving
from . import profiler
from . import rtc
from . import torch_bridge
from .torch_bridge import th
from . import visualization
from . import visualization as viz
from . import image
from . import recordio
from . import test_utils

# DMLC_ROLE=server processes become parameter servers on import (reference
# python/mxnet/kvstore_server.py _init_kvstore_server_module)
from .kvstore_server import _init_kvstore_server_module as _srv_init
_srv_init()
del _srv_init
