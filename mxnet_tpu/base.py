"""Core shared plumbing: errors, registries, name management.

TPU-native re-design of the reference's ``python/mxnet/base.py`` (ctypes lib
loading, handle types, error translation — reference ``python/mxnet/base.py:1-258``).
There is no C handle layer here: the "backend" is JAX/XLA, so this module only
keeps the pieces that are real API surface — the exception type, the generic
registry used by optimizers/initializers/metrics/iterators, and name management
for auto-generated symbol names (reference ``python/mxnet/name.py``).
"""

from __future__ import annotations

import os
import threading

__all__ = ["MXNetError", "Registry", "NameManager", "Prefix", "string_types",
           "atomic_write", "atomic_write_bytes"]

string_types = (str,)


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: ``base.py:42`` MXNetError)."""


def _fsync_dir(path):
    """fsync the directory entry so a completed rename survives a crash."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return  # exotic filesystems may refuse O_RDONLY on a dir
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path, write_fn, fault_point=None, durable=True):
    """Crash-safe file write: ``write_fn(tmp_path)`` → fsync → atomic
    rename onto ``path`` (checkpoints, manifests, optimizer states).

    A reader never observes a half-written ``path``: either the old
    content (or nothing) or the complete new content.  ``fault_point``
    names a :mod:`mxnet_tpu.faults` injection point; when armed and
    firing, the temp file is truncated and :class:`faults.FaultInjected`
    raised — the on-disk state of a host dying mid-write (the rename
    never happens, the previous ``path`` stays intact).

    ``durable=False`` skips the fsyncs (file + directory): the rename
    is still atomic against PROCESS death — the preemption threat model,
    where the kernel and page cache survive — but the bytes may be lost
    to a power/kernel crash.  The batch-granular snapshot path uses it
    (a snapshot's value is measured in batches; the fully-durable epoch
    checkpoint is never more than an epoch behind), keeping the writer
    off the fsync stalls."""
    from . import faults as _faults  # lazy: faults imports base

    tmp = "%s.tmp-%d" % (path, os.getpid())
    try:
        write_fn(tmp)
        if fault_point is not None and _faults.should_fire(fault_point):
            size = os.path.getsize(tmp)
            with open(tmp, "r+b") as f:
                f.truncate(max(0, size // 2))
            raise _faults.FaultInjected(
                "fault %r: write of %s killed mid-file"
                % (fault_point, path))
        if durable:
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        os.replace(tmp, path)
        if durable:
            _fsync_dir(path)
    except _faults.FaultInjected:
        raise  # simulated crash: leave the truncated temp file behind
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_bytes(path, data, mode="wb", fault_point=None,
                       durable=True):
    """:func:`atomic_write` of a ready blob.  Closes (flushes) the temp
    file before the fsync+rename — ``lambda tmp: open(tmp).write(data)``
    call sites would lean on refcount finalization for the flush, which
    only CPython guarantees."""
    def _write(tmp):
        with open(tmp, mode) as f:
            f.write(data)
    atomic_write(path, _write, fault_point=fault_point, durable=durable)


class Registry:
    """A named registry of classes/functions with alias support.

    Single replacement for the reference's many ad-hoc registries
    (optimizer ``optimizer.py:71``, metric ``metric.py``, initializer,
    image augmenters, io iterators).
    """

    def __init__(self, kind):
        self._kind = kind
        self._entries = {}

    def register(self, obj=None, name=None):
        def _do(o):
            key = (name or getattr(o, "__name__", None) or str(o)).lower()
            self._entries[key] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def alias(self, obj, *names):
        for n in names:
            self._entries[n.lower()] = obj
        return obj

    def get(self, name):
        key = str(name).lower()
        if key not in self._entries:
            raise MXNetError(
                "%s %r is not registered (known: %s)"
                % (self._kind, name, sorted(self._entries))
            )
        return self._entries[key]

    def create(self, name, *args, **kwargs):
        return self.get(name)(*args, **kwargs)

    def __contains__(self, name):
        return str(name).lower() in self._entries

    def keys(self):
        return sorted(self._entries)


class NameManager:
    """Auto-naming for symbols (reference ``python/mxnet/name.py:6-60``).

    Thread-local stack so `with NameManager():` scopes compose; the current
    manager assigns ``op_name + running count`` names to anonymous symbols.
    """

    _tls = threading.local()

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if not hasattr(NameManager._tls, "stack"):
            NameManager._tls.stack = [NameManager()]
        NameManager._tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        NameManager._tls.stack.pop()

    @staticmethod
    def current():
        if not hasattr(NameManager._tls, "stack"):
            NameManager._tls.stack = [NameManager()]
        return NameManager._tls.stack[-1]


class Prefix(NameManager):
    """NameManager that prepends a fixed prefix (reference ``name.py:63``)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)
