"""Weight initializers (``mx.init``).

Reference: ``python/mxnet/initializer.py`` (SURVEY §2.6) — name-pattern
dispatch via ``InitDesc``/``__call__``, registry of Uniform/Normal/Xavier/
MSRAPrelu/Orthogonal/Bilinear/LSTMBias/Load/Mixed.
"""

from __future__ import annotations

import json
import re

import numpy as np

from .base import Registry

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Zero", "One",
           "Constant", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "FusedRNN", "Load", "Mixed", "registry", "create"]

registry = Registry("initializer")


class InitDesc(str):
    """Name + attrs descriptor (reference ``initializer.py`` InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base: dispatch by name pattern (reference ``initializer.py:20``)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be str/InitDesc")
        attrs = getattr(desc, "attrs", {})
        if "__init__" in attrs:
            klass, kwargs = json.loads(attrs["__init__"])
            registry.create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("state") or name.endswith("state_cell"):
            # initial hidden/cell state arguments of the fused RNN op
            self._init_zero(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- slot initializers ------------------------------------------------
    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError()

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s (reference raises too)" % name)


@registry.register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape) \
            .astype(np.float32)


@registry.register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape).astype(np.float32)


@registry.register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


registry.alias(Zero, "zeros")


@registry.register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


registry.alias(One, "ones")


@registry.register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@registry.register
class Orthogonal(Initializer):
    """reference ``initializer.py`` Orthogonal (Saxe et al.)"""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _v, q = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == (nout, nin) else q
        arr[:] = (self.scale * res).reshape(arr.shape).astype(np.float32)


@registry.register
class Xavier(Initializer):
    """reference ``initializer.py:344``"""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, _, arr):
        shape = arr.shape
        hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = shape[1] * hw_scale if len(shape) > 1 else shape[0]
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape).astype(np.float32)
        else:
            arr[:] = np.random.normal(0, scale, shape).astype(np.float32)


@registry.register
class MSRAPrelu(Xavier):
    """reference initializer.py MSRAPrelu (He init w/ prelu slope)"""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@registry.register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        Initializer._init_bilinear(self, name, arr)


@registry.register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        num_hidden = arr.shape[0] // 4
        v = np.zeros(arr.shape, dtype=np.float32)
        v[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = v

    _init_bias = _init_weight


@registry.register
class FusedRNN(Initializer):
    """Initialize a fused RNN parameter blob (reference initializer.py
    ``FusedRNN``): each packed weight matrix gets ``init``, biases get zero,
    and LSTM forget-gate biases get ``forget_bias``."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = registry.create(klass, **kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        # round-trip through the cell's own blob layout (reference FusedRNN
        # does the same): unpack -> init each piece -> pack back
        from .rnn.rnn_cell import FusedRNNCell

        global_init = getattr(desc, "global_init", None)
        inner = self._init
        if inner is None:
            # fall back to the surrounding global initializer (reference
            # FusedRNN does the same via desc.global_init).  If that is
            # itself a FusedRNN (user passed one explicitly while the cell
            # variable already carries the attr), use ITS inner init —
            # re-entering blob unpacking on a per-layer piece would crash.
            fallback = global_init
            while isinstance(fallback, FusedRNN):
                fallback = fallback._init
            inner = fallback or Uniform(0.07)
        cell = FusedRNNCell(self._num_hidden, num_layers=self._num_layers,
                            mode=self._mode,
                            bidirectional=self._bidirectional,
                            forget_bias=self._forget_bias, prefix="")
        args = cell.unpack_weights({"parameters": arr})
        h = self._num_hidden
        for name, value in args.items():
            # fresh per-piece desc: name-based dispatch on the piece, no
            # __init__ attr, so no recursion (reference FusedRNN builds
            # InitDesc(name, global_init=desc.global_init) the same way)
            piece = InitDesc(name, global_init=global_init)
            if name.endswith("weight"):
                if hasattr(inner, "_init_weight"):
                    inner._init_weight(piece, value)
                else:
                    # dispatching initializer without slots (e.g. Mixed):
                    # full call so the piece name picks the right entry
                    inner(piece, value)
            else:
                bias = np.zeros(value.shape[0], dtype=np.float32)
                if self._mode == "lstm":
                    bias[h:2 * h] = self._forget_bias
                value[:] = bias
        arr[:] = cell.pack_weights(args)["parameters"]

    _init_default = _init_weight


@registry.register
class Load:
    """Init from a saved param dict (reference initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load

            param = load(param)
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if src.shape != arr.shape:
                raise ValueError("Load: shape mismatch for %s" % name)
            src.copyto(arr)
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise ValueError("Load: no init for %s" % name)


@registry.register
class Mixed:
    """Pattern-matched initializer list (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise ValueError("Mixed: no pattern matches %s" % name)


def create(name, **kwargs):
    return registry.create(name, **kwargs)
