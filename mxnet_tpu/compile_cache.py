"""Compile-once infrastructure: persistent XLA compile cache + AOT
warm-up manifests.

Every process used to pay the full trace+compile cost from scratch:
serving warm-up, CI, bench and ``resume="auto"`` all re-compiled
executables whose HLO fingerprints :mod:`mxnet_tpu.perfdebug` already
records.  This module treats compiled executables as durable, reusable
artifacts — the whole-program-compilation idiom of AOT-XLA (Julia→TPU)
and TVM's compiled-kernel artifact reuse — in two tiers:

**Tier 1 — the persistent compilation cache.**  ``MXNET_COMPILE_CACHE_DIR``
(or :func:`enable`) points JAX's persistent compilation cache at a
directory: every XLA compile first consults the on-disk cache and only
compiles on a miss, writing the serialized executable back for the next
process.  This module owns the operational half the raw JAX knob lacks:

* size/GC bounds — ``MXNET_COMPILE_CACHE_MAX_BYTES`` caps the directory,
  :func:`gc` evicts least-recently-used entries (the ``-atime`` sidecar
  files JAX maintains are the recency signal) and keeps the
  ``xla.compile.persistent_cache_bytes``/``_entries`` gauges fresh;
* corruption safety — a corrupt/truncated entry is NEVER fatal: reads go
  through JAX's non-raising path (we pin
  ``jax_raise_persistent_cache_errors=False``), so a torn entry logs a
  warning, recompiles cleanly and self-heals by overwriting the entry.
  :func:`verify` sweeps undecodable entries out of the directory, and
  everything THIS module writes (manifests) goes through
  ``base.atomic_write``.  The ``compile_cache.read`` fault point
  (:mod:`mxnet_tpu.faults`) truncates a real entry mid-read so the
  fallback is deterministically testable;
* telemetry — persistent hits/misses/saved-seconds are counted under
  ``xla.compile.persistent_cache_*``, SPLIT from the in-process jit
  function cache (``xla.compile.fn_cache_hits`` in ``executor.py``):
  "cold" below always means an actual ``backend.compile`` ran
  (= a persistent-cache miss, or the cache is off).

**Tier 2 — AOT warm-up manifests.**  While recording
(:func:`recording`, implied by tier 1), every executor jit build is
noted with its full identity: executor name, kind, abstract call
signature (shapes/dtypes pytree), shape-signature hash and the
normalized HLO fingerprint from :mod:`mxnet_tpu.perfdebug`.
:func:`save_manifest` persists those entries next to the artifact they
describe — ``<model_dir>/warmup.json`` for a served model,
``<checkpoint_prefix>-warmup.json`` for a training run — and replay
(``Executor.precompile`` / ``Module.warm_from_manifest`` /
``serving.ModelRegistry`` load/reload) AOT-lowers-and-compiles every
recorded program BEFORE traffic or training resumes.  With tier 1
populated the replay is pure cache loads: a version swap or preemption
restart performs **zero cold compiles** on the hot path.  Invalidation
is the HLO fingerprint: a replayed program lowering to different HLO
than the manifest recorded logs a ``compile_cache.fingerprint_change``
event (the manifest is then rewritten from the fresh build).

Cost model: recording adds ONE extra trace (an AOT ``lower``) per jit
build to fingerprint the program — never a second XLA compile, never
any steady-state dispatch cost.  Disabled, every hook is one boolean
check.

See docs/how_to/perf.md "Compile once".
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time

import numpy as np

from . import faults as _faults
from . import perfdebug as _perfdebug
from . import telemetry as _telemetry
from .base import MXNetError, atomic_write

__all__ = [
    "enabled", "recording", "enable", "disable", "cache_dir", "stats",
    "cache_entries", "cache_size_bytes", "gc", "verify", "note_build",
    "instrument", "records", "recording_scope", "reset_records",
    "manifest_path", "save_manifest", "save_manifest_if_changed",
    "load_manifest", "kind_to_json", "kind_from_json",
    "signature_to_json", "signature_from_json", "MANIFEST_VERSION",
]

_log = logging.getLogger("mxnet_tpu.compile_cache")

#: warm-up manifest schema version (bumped on incompatible changes;
#: :func:`load_manifest` rejects unknown versions)
MANIFEST_VERSION = 1

#: suffixes of one persistent-cache entry: JAX writes the compressed
#: serialized executable to ``<key>-cache`` and touches ``<key>-atime``
#: on every read — the recency signal :func:`gc` evicts by
_CACHE_SUFFIX = "-cache"
_ATIME_SUFFIX = "-atime"

_lock = threading.Lock()
_dir = None            # active cache directory (None = tier 1 off)
_max_bytes = 0         # GC bound (0 = unbounded)
_records = []          # tier-2 build records, in build order
_record_seq = 0        # monotonic build stamp (recording_scope cursor)
_saved_manifests = {}  # path -> content hash (save_manifest_if_changed)
_listening = False     # jax.monitoring listeners installed
_orig_get = None       # unwrapped compilation_cache.get_executable_and_time

# process-local persistent-cache counters: kept even when telemetry is
# disabled so stats() (and the CI cache-effectiveness check) always work
_hits = 0
_misses = 0
_saved_seconds = 0.0
_evictions = 0
_corrupt_dropped = 0

_COUNTERS = (
    "xla.compile.persistent_cache_hits",
    "xla.compile.persistent_cache_misses",
    "xla.compile.persistent_cache_evictions",
    "xla.compile.persistent_cache_corrupt_dropped",
)


# -- enablement -------------------------------------------------------------
def enabled():
    """True when the persistent compile cache (tier 1) is active."""
    return _dir is not None


def recording():
    """True when jit builds are recorded into the warm-up manifest
    registry (tier 2) — implied by :func:`enabled`; the one check the
    executor's build path makes."""
    return _dir is not None


def cache_dir():
    """The active cache directory, or None."""
    return _dir


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return float(default)


def enable(directory=None, max_bytes=None):
    """Activate the two-tier compile cache.

    ``directory`` defaults to ``MXNET_COMPILE_CACHE_DIR``; ``max_bytes``
    to ``MXNET_COMPILE_CACHE_MAX_BYTES`` (0 = unbounded).  Configures
    JAX's persistent compilation cache (min-compile-time floor from
    ``MXNET_COMPILE_CACHE_MIN_COMPILE_SECS``, default 0 so every
    program is cached; corrupt-entry reads NON-fatal), installs the
    hit/miss telemetry listeners, sweeps zero-length entries (full
    decode verification with ``MXNET_COMPILE_CACHE_VERIFY=1``) and
    enforces the size bound.  Idempotent; safe to call after compiles
    already happened (JAX's cached "cache unused" verdict is reset)."""
    global _dir, _max_bytes
    directory = directory or os.environ.get("MXNET_COMPILE_CACHE_DIR", "")
    if not directory:
        raise MXNetError(
            "compile_cache.enable needs a directory (argument or "
            "MXNET_COMPILE_CACHE_DIR)")
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    if max_bytes is None:
        max_bytes = _env_int("MXNET_COMPILE_CACHE_MAX_BYTES", 0)
    import jax
    from jax._src import compilation_cache as _jcc

    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("MXNET_COMPILE_CACHE_MIN_COMPILE_SECS", "0")
              or 0.0))
    # cache every executable: the tiny ones are exactly what a serving
    # warm-up / resume replays by the dozen
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # the corruption contract: a torn entry warns + recompiles, never
    # raises into the dispatch that wanted the executable
    jax.config.update("jax_raise_persistent_cache_errors", False)
    # compiles that ran before enable() memoized "cache unused" — drop
    # that verdict (and any stale cache object) so this process caches
    _jcc.reset_cache()
    with _lock:
        _dir = directory
        _max_bytes = max(0, int(max_bytes or 0))
    _install_listeners()
    _install_read_fault_shim()
    if _telemetry.enabled():
        _telemetry.declare(*_COUNTERS)
    dropped = verify(
        deep=os.environ.get("MXNET_COMPILE_CACHE_VERIFY", "0")
        not in ("0", "", "false"))
    evicted = gc()
    _telemetry.event("compile_cache.enabled", dir=directory,
                     max_bytes=_max_bytes, corrupt_dropped=dropped,
                     evicted=evicted)
    _log.info("compile_cache: persistent XLA compile cache at %s "
              "(max_bytes=%s, %d entries / %d bytes)", directory,
              _max_bytes or "unbounded", cache_entries(),
              cache_size_bytes())
    return directory


def disable():
    """Deactivate tier 1 + tier 2 recording (entries on disk are kept)."""
    global _dir
    import jax
    from jax._src import compilation_cache as _jcc

    with _lock:
        _dir = None
    jax.config.update("jax_enable_compilation_cache", False)
    jax.config.update("jax_compilation_cache_dir", None)
    _jcc.reset_cache()


def _init_from_env():
    """Package-import hook: arm from ``MXNET_COMPILE_CACHE_DIR`` when
    set; never raises (a bad cache dir must not break import)."""
    if _dir is not None or not os.environ.get("MXNET_COMPILE_CACHE_DIR"):
        return
    try:
        enable()
    except Exception as e:  # noqa: broad-except — import-time guard
        _log.warning("compile_cache: could not enable from "
                     "MXNET_COMPILE_CACHE_DIR: %s", e)


# -- telemetry listeners ----------------------------------------------------
_EVENT_HITS = "/jax/compilation_cache/cache_hits"
_EVENT_MISSES = "/jax/compilation_cache/cache_misses"
_EVENT_SAVED = "/jax/compilation_cache/compile_time_saved_sec"
_EVENT_RETRIEVAL = "/jax/compilation_cache/cache_retrieval_time_sec"


def _on_event(event, **_kw):
    global _hits, _misses
    if _dir is None:
        return
    if event == _EVENT_HITS:
        with _lock:
            _hits += 1
        _telemetry.inc("xla.compile.persistent_cache_hits")
    elif event == _EVENT_MISSES:
        with _lock:
            _misses += 1
        _telemetry.inc("xla.compile.persistent_cache_misses")


def _on_duration(event, duration, **_kw):
    global _saved_seconds
    if _dir is None:
        return
    if event == _EVENT_SAVED:
        with _lock:
            _saved_seconds += max(0.0, float(duration))
        _telemetry.observe("xla.compile.persistent_cache_saved_seconds",
                           duration)
    elif event == _EVENT_RETRIEVAL:
        _telemetry.observe("xla.compile.persistent_cache_retrieval_seconds",
                           duration)


def _install_listeners():
    """Register the jax.monitoring listeners exactly once per process
    (jax offers no unregister; the callbacks early-return when this
    module is disabled)."""
    global _listening
    if _listening:
        return
    import jax.monitoring as _mon

    _mon.register_event_listener(_on_event)
    _mon.register_event_duration_secs_listener(_on_duration)
    _listening = True


# -- corrupt-entry fault point ----------------------------------------------
def _truncate_entry(cache_key):
    """Tear the on-disk entry for ``cache_key`` in half — the state a
    host crash mid-cache-write leaves behind."""
    if _dir is None:
        return
    path = os.path.join(_dir, cache_key + _CACHE_SUFFIX)
    try:
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        _log.warning("fault 'compile_cache.read': truncated cache entry "
                     "%s to %d bytes", path, max(1, size // 2))
    except OSError as e:
        _log.warning("fault 'compile_cache.read': could not truncate "
                     "%s: %s", path, e)


def _install_read_fault_shim():
    """Wrap persistent-cache reads twice over:

    * the ``compile_cache.read`` fault point — when armed and firing,
      the REAL on-disk entry is truncated immediately before JAX reads
      it, so tests exercise the genuine corrupt-entry path (decode
      failure → warning → clean recompile), not a simulation of it;
    * self-healing — JAX's ``LRUCache.put`` is a no-op when the entry
      file already exists, so a torn entry would otherwise stay torn
      FOREVER (every future process warns + recompiles).  A failed read
      therefore drops the torn entry here, letting the recompile's
      write-back land a healthy one."""
    global _orig_get
    if _orig_get is not None:
        return
    from jax._src import compilation_cache as _jcc

    _orig_get = _jcc.get_executable_and_time

    def _guarded(cache_key, compile_options, backend):
        if _dir is not None and _faults.should_fire("compile_cache.read"):
            _truncate_entry(cache_key)
        try:
            return _orig_get(cache_key, compile_options, backend)
        except Exception:
            if _dir is not None:
                _drop_entry(cache_key,
                            os.path.join(_dir, cache_key + _CACHE_SUFFIX),
                            "corrupt")
                _log.warning(
                    "compile_cache: dropped torn persistent-cache entry "
                    "%s after a failed read; the recompile will rewrite "
                    "it", cache_key)
            raise  # jax's non-raising read path turns this into a miss

    _jcc.get_executable_and_time = _guarded


# -- size accounting / GC / verification ------------------------------------
def _entry_list():
    """[(key, cache_path, bytes, atime_seconds)] for every on-disk
    entry, oldest-read first."""
    if _dir is None:
        return []
    out = []
    try:
        names = os.listdir(_dir)
    except OSError:
        return []
    for name in names:
        if not name.endswith(_CACHE_SUFFIX):
            continue
        key = name[:-len(_CACHE_SUFFIX)]
        path = os.path.join(_dir, name)
        try:
            size = os.path.getsize(path)
        except OSError:
            continue  # racing eviction
        atime_path = os.path.join(_dir, key + _ATIME_SUFFIX)
        try:
            atime = os.path.getmtime(atime_path)
        except OSError:
            try:
                atime = os.path.getmtime(path)
            except OSError:
                atime = 0.0
        out.append((key, path, size, atime))
    out.sort(key=lambda e: e[3])
    return out


def cache_entries():
    """Number of executables currently on disk."""
    return len(_entry_list())


def cache_size_bytes():
    """Total bytes of cached executables on disk."""
    return sum(e[2] for e in _entry_list())


def _refresh_gauges(entries=None):
    if entries is None:
        entries = _entry_list()
    _telemetry.set_gauge("xla.compile.persistent_cache_bytes",
                         sum(e[2] for e in entries))
    _telemetry.set_gauge("xla.compile.persistent_cache_entries",
                         len(entries))


def _drop_entry(key, path, counter):
    global _evictions, _corrupt_dropped
    for p in (path, os.path.join(_dir, key + _ATIME_SUFFIX)):
        try:
            os.unlink(p)
        except OSError:
            pass
    if counter == "evicted":
        with _lock:
            _evictions += 1
        _telemetry.inc("xla.compile.persistent_cache_evictions")
    else:
        with _lock:
            _corrupt_dropped += 1
        _telemetry.inc("xla.compile.persistent_cache_corrupt_dropped")


def gc(max_bytes=None):
    """Evict least-recently-used entries until the directory is within
    ``max_bytes`` (default: the bound :func:`enable` was given; 0 =
    unbounded).  Returns the number of evicted entries and refreshes the
    size gauges either way."""
    if _dir is None:
        return 0
    bound = _max_bytes if max_bytes is None else max(0, int(max_bytes))
    entries = _entry_list()
    evicted = 0
    if bound > 0:
        total = sum(e[2] for e in entries)
        while entries and total > bound:
            key, path, size, _atime = entries.pop(0)  # oldest read first
            _drop_entry(key, path, "evicted")
            total -= size
            evicted += 1
            _log.info("compile_cache: evicted %s (%d bytes) — cache over "
                      "the %d-byte bound", key, size, bound)
    _refresh_gauges(entries)
    return evicted


def verify(deep=False):
    """Drop undecodable entries: zero-length always; with ``deep=True``
    every entry is decompressed + split (the full integrity check JAX
    would otherwise only perform lazily at read time).  Returns the
    number of dropped entries."""
    if _dir is None:
        return 0
    dropped = 0
    entries = _entry_list()
    for key, path, size, _atime in entries:
        bad = size == 0
        if not bad and deep:
            try:
                from jax._src import compilation_cache as _jcc

                with open(path, "rb") as f:
                    blob = f.read()
                _jcc.extract_executable_and_time(
                    _jcc.decompress_executable(blob))
            except Exception:  # noqa: broad-except — any decode error
                # means the entry can never load; drop it
                bad = True
        if bad:
            _drop_entry(key, path, "corrupt")
            dropped += 1
            _log.warning("compile_cache: dropped corrupt/truncated cache "
                         "entry %s", key)
    if dropped:
        _refresh_gauges()
    return dropped


_size_memo = (None, 0, 0)  # (mutation stamp, entries, bytes)


def _sized():
    """(entries, bytes) of the on-disk cache, rescanned only when a
    mutation counter moved since the last scan — new entries appear
    exactly on misses, disappear on evictions/corrupt drops — so the
    polled consumers (``/healthz``, per-warmup stats deltas) don't pay
    O(entries) stat calls per read."""
    global _size_memo
    with _lock:
        stamp = (_dir, _misses, _evictions, _corrupt_dropped)
        if stamp == _size_memo[0]:
            return _size_memo[1], _size_memo[2]
    entries = _entry_list()
    n, b = len(entries), sum(e[2] for e in entries)
    with _lock:
        _size_memo = (stamp, n, b)
    return n, b


def stats():
    """Operational snapshot: enabled/dir/entries/bytes plus the
    process-local persistent hit/miss/saved/eviction counters (tracked
    independently of telemetry enablement, so the CI effectiveness check
    and ``/healthz`` always see them)."""
    n_entries, n_bytes = _sized()
    with _lock:
        return {
            "enabled": _dir is not None,
            "dir": _dir,
            "entries": n_entries,
            "bytes": n_bytes,
            "max_bytes": _max_bytes,
            "hits": _hits,
            "misses": _misses,
            "compile_time_saved_seconds": round(_saved_seconds, 3),
            "evictions": _evictions,
            "corrupt_dropped": _corrupt_dropped,
            "recorded_builds": len(_records),
        }


# -- tier 2: build recording ------------------------------------------------
#: executor kind families the replay path can reconstruct; anything else
#: (placement segments, module-level fused updates) is recorded for the
#: report but skipped by ``Executor.precompile``
REPLAYABLE_KINDS = frozenset({
    "predict", "train", "train_guard", "train_fwd", "train_with_grads",
    "train_sgd", "train_sgd_scan", "predict_scan",
})


def kind_to_json(kind):
    """Executor kind (a string, or a nested tuple of strings/numbers/
    bools) → JSON-safe form, exactly invertible by
    :func:`kind_from_json`."""
    if isinstance(kind, str):
        return kind
    if isinstance(kind, tuple):
        return {"t": "tuple", "items": [kind_to_json(k) for k in kind]}
    if kind is None or isinstance(kind, (bool, int, float)):
        return {"t": "py", "v": kind}
    raise MXNetError("unserializable executor kind element %r" % (kind,))


def kind_from_json(obj):
    if isinstance(obj, str):
        return obj
    if isinstance(obj, dict):
        if obj.get("t") == "tuple":
            return tuple(kind_from_json(i) for i in obj["items"])
        if obj.get("t") == "py":
            return obj["v"]
    raise MXNetError("unreadable manifest kind %r" % (obj,))


def _abstractify(tree):
    """Shapes/dtypes/shardings of a call tree: like ``perfdebug``'s
    abstractify, but a leaf committed to one device keeps its
    ``SingleDeviceSharding`` — committed args lower with an
    ``mhlo.sharding`` annotation, so dropping it would fingerprint (and
    persistent-cache-key) a DIFFERENT program than the real dispatch
    compiles."""
    import jax
    from jax.sharding import SingleDeviceSharding

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sh = getattr(x, "sharding", None)
            if isinstance(sh, SingleDeviceSharding):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def _dtype_name(dt):
    return np.dtype(dt).name


def _dtype_from_name(name):
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp

        return np.dtype(getattr(jnp, name))


def _sig_to_json(x):
    if x is None or isinstance(x, (bool, int, float, str)):
        return {"t": "py", "v": x}
    if isinstance(x, (list, tuple)):
        return {"t": "tuple" if isinstance(x, tuple) else "list",
                "items": [_sig_to_json(i) for i in x]}
    if isinstance(x, dict):
        return {"t": "dict",
                "items": {k: _sig_to_json(v) for k, v in sorted(x.items())}}
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        from jax.sharding import SingleDeviceSharding

        node = {"t": "a", "s": [int(d) for d in x.shape],
                "d": _dtype_name(x.dtype)}
        if isinstance(getattr(x, "sharding", None), SingleDeviceSharding):
            # replay re-pins onto the REPLAYING executor's device
            node["sh"] = "single"
        return node
    raise MXNetError("unserializable signature leaf %r" % type(x))


def _sig_from_json(obj, device):
    import jax

    t = obj.get("t")
    if t == "py":
        return obj["v"]
    if t == "list":
        return [_sig_from_json(i, device) for i in obj["items"]]
    if t == "tuple":
        return tuple(_sig_from_json(i, device) for i in obj["items"])
    if t == "dict":
        return {k: _sig_from_json(v, device)
                for k, v in obj["items"].items()}
    if t == "a":
        sharding = None
        if obj.get("sh") == "single" and device is not None:
            from jax.sharding import SingleDeviceSharding

            sharding = SingleDeviceSharding(device)
        return jax.ShapeDtypeStruct(tuple(obj["s"]),
                                    _dtype_from_name(obj["d"]),
                                    sharding=sharding)
    raise MXNetError("unreadable manifest signature node %r" % (obj,))


def signature_to_json(args, kwargs):
    """Abstract call signature (shapes/dtypes/shardings pytree of a jit
    call) → JSON-safe form.  List/tuple/dict structure is preserved
    exactly — jit treats them as distinct pytrees, so replay must
    too."""
    return {"args": [_sig_to_json(a) for a in args],
            "kwargs": {k: _sig_to_json(v)
                       for k, v in sorted((kwargs or {}).items())}}


def signature_from_json(sig, device=None):
    """Inverse of :func:`signature_to_json`: ``(args, kwargs)`` of
    ``jax.ShapeDtypeStruct`` leaves, ready for ``fn.lower(*args,
    **kwargs)``.  ``device`` re-pins single-device-committed leaves so
    the replayed lowering carries the same sharding annotations (and
    therefore the same persistent-cache key) as the real dispatch."""
    args = [_sig_from_json(a, device) for a in sig.get("args", [])]
    kwargs = {k: _sig_from_json(v, device)
              for k, v in sig.get("kwargs", {}).items()}
    return args, kwargs


def note_build(exec_name, kind, lower_fn, args, kwargs=None, seconds=None):
    """Record one freshly built executable into the warm-up registry:
    abstractify the call, AOT-lower it once for the normalized HLO
    fingerprint (``MXNET_COMPILE_CACHE_FINGERPRINT=0`` skips the extra
    trace), and store the full replayable identity.  Never raises into
    the build path.  Returns the entry dict or None."""
    if not recording():
        return None
    try:
        return _note_build_impl(exec_name, kind, lower_fn, args,
                                kwargs or {}, seconds)
    except Exception as e:  # noqa: broad-except — recording failure
        # must never break the dispatch that triggered it
        _log.debug("compile_cache: note_build failed for %s/%s: %s",
                   exec_name, kind, e)
        return None


def _note_build_impl(exec_name, kind, lower_fn, args, kwargs, seconds):
    sds_args = _abstractify(args)
    sds_kwargs = _abstractify(kwargs)
    fingerprint = None
    if lower_fn is not None and \
            os.environ.get("MXNET_COMPILE_CACHE_FINGERPRINT", "1") \
            not in ("0", "", "false"):
        try:
            lowered = lower_fn(*sds_args, **sds_kwargs)
            fingerprint = _perfdebug.fingerprint_text(lowered.as_text())
        except Exception as e:  # noqa: broad-except — a program that
            # cannot re-lower abstractly still warms the cache; it just
            # loses invalidation detection
            _log.debug("compile_cache: fingerprint of %s/%s failed: %s",
                       exec_name, kind, e)
    kind_name = kind if isinstance(kind, str) else str(kind[0])
    entry = {
        "exec": exec_name,
        "kind": kind_to_json(kind),
        "kind_name": kind_name,
        "shapes": _perfdebug._shape_sig(sds_args, sds_kwargs),
        "fingerprint": fingerprint,
        "compile_seconds": round(seconds, 4) if seconds else None,
        "sig": signature_to_json(sds_args, sds_kwargs),
    }
    global _record_seq
    with _lock:
        # one entry per identity; a rebuild refreshes the entry and its
        # sequence stamp, so a recording_scope() sees identities rebuilt
        # inside it (a model reload re-builds programs the first load
        # already recorded)
        _record_seq += 1
        entry["_seq"] = _record_seq
        for i, old in enumerate(_records):
            if (old["exec"], old["kind"], old["shapes"]) == \
                    (entry["exec"], entry["kind"], entry["shapes"]):
                _records.pop(i)
                break
        _records.append(entry)
    _telemetry.inc("compile_cache.builds_recorded", kind=kind_name)
    return entry


def instrument(fn, name, kind):
    """Wrap jitted ``fn`` so its first call is recorded into the warm-up
    registry (via perfdebug's shared first-call wrapper); returns ``fn``
    unchanged when recording is off."""
    if not recording():
        return fn
    return _perfdebug.first_call_hook(
        fn, lambda f, args, kwargs, dt: note_build(name, kind, f.lower,
                                                   args, kwargs, dt))


def _public(entry):
    return {k: v for k, v in entry.items() if not k.startswith("_")}


def records():
    """Every recorded build this process, in build order (copies)."""
    with _lock:
        return [_public(e) for e in _records]


def reset_records():
    """Clear the tier-2 registry, save memos and the process-local
    persistent-cache counters (tests)."""
    global _hits, _misses, _saved_seconds, _evictions, _corrupt_dropped
    with _lock:
        _records.clear()
        _saved_manifests.clear()
        _hits = _misses = _evictions = _corrupt_dropped = 0
        _saved_seconds = 0.0


class recording_scope:
    """Context manager capturing the builds (and REbuilds — sequence
    stamps, not list positions) recorded inside its scope — how a
    serving warm-up collects exactly ITS model's entries.  Usable
    (empty) when recording is off."""

    def __init__(self):
        self._start = 0
        self.entries = []

    def __enter__(self):
        with _lock:
            self._start = _record_seq
        return self

    def __exit__(self, *exc):
        with _lock:
            self.entries = [_public(e) for e in _records
                            if e["_seq"] > self._start]
        return False


# -- manifests --------------------------------------------------------------
def manifest_path(prefix):
    """Canonical warm-up manifest path for a checkpoint prefix."""
    return "%s-warmup.json" % prefix


def _manifest_payload(entries, model):
    import jax

    return {
        "version": MANIFEST_VERSION,
        "jax": jax.__version__,
        "model": model,
        "ts": int(time.time()),
        "entries": entries,
    }


def save_manifest(path, entries=None, model=None):
    """Persist a warm-up manifest atomically (``base.atomic_write``);
    ``entries`` defaults to every build recorded this process.  Returns
    ``path``."""
    if entries is None:
        entries = records()
    payload = json.dumps(_manifest_payload(entries, model), indent=1,
                         sort_keys=True)

    def _write(tmp):
        with open(tmp, "w") as f:
            f.write(payload)

    atomic_write(path, _write)
    with _lock:
        _saved_manifests[path] = hashlib.sha256(
            json.dumps(entries, sort_keys=True).encode()).hexdigest()
    _telemetry.inc("compile_cache.manifest.saves")
    return path


def save_manifest_if_changed(path, entries=None, model=None):
    """:func:`save_manifest`, skipped when ``entries`` match what this
    process last wrote to ``path`` (the checkpoint cadence calls this
    every epoch/snapshot; the manifest goes static after the first
    batch).  Never raises — a manifest write failure must not break a
    checkpoint.  Returns the path when written, else None."""
    if entries is None:
        entries = records()
    if not entries:
        return None
    digest = hashlib.sha256(
        json.dumps(entries, sort_keys=True).encode()).hexdigest()
    with _lock:
        if _saved_manifests.get(path) == digest:
            return None
    try:
        return save_manifest(path, entries=entries, model=model)
    except Exception as e:  # noqa: broad-except — best-effort sidecar
        _log.warning("compile_cache: could not write warm-up manifest "
                     "%s: %s", path, e)
        return None


def load_manifest(path):
    """Read a warm-up manifest; returns the dict, or None when absent,
    torn or from an unknown schema version (counted + logged — a bad
    manifest degrades to a cold start, never an error)."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            man = json.load(f)
        if not isinstance(man, dict):
            raise ValueError("manifest top level is %s, not an object"
                             % type(man).__name__)
        if man.get("version") != MANIFEST_VERSION:
            raise ValueError("manifest version %r (want %d)"
                             % (man.get("version"), MANIFEST_VERSION))
        if not isinstance(man.get("entries"), list):
            raise ValueError("manifest carries no entry list")
        return man
    except (OSError, ValueError) as e:
        _telemetry.inc("compile_cache.manifest.corrupt")
        _log.warning("compile_cache: unreadable warm-up manifest %s "
                     "(%s); warm-up degrades to lazy compilation",
                     path, e)
        return None
