"""Data iterators (``mx.io``).

Reference: ``python/mxnet/io.py`` + ``src/io/`` (SURVEY §2.5): ``DataIter``
ABC, ``NDArrayIter``, ``MNISTIter`` (idx format, distributed part_index
sharding — ``src/io/iter_mnist.cc``), ``CSVIter``, ``ResizeIter``,
``PrefetchingIter`` (the ``iter_prefetcher.h`` background double-buffer).

TPU notes: batches land on device via the NDArray layer; PrefetchingIter
overlaps host decode with device compute (the PJRT async dispatch gives the
copy/compute overlap the reference got from pinned-memory copy workers).
"""

from __future__ import annotations

import gzip
import logging
import os
import struct
import threading

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "MNISTIter",
           "CSVIter", "ResizeIter", "PrefetchingIter", "DevicePrefetchIter",
           "ElasticShardIter", "ImageRecordIter", "corrupt_skip_count",
           "reset_corrupt_skip_count"]


class DataDesc:
    """(name, shape, dtype, layout) — reference io.py DataDesc namedtuple."""

    def __init__(self, name, shape, dtype=np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __iter__(self):
        yield self.name
        yield self.shape

    def __getitem__(self, i):
        return (self.name, self.shape)[i]

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    def __eq__(self, other):
        return tuple(self) == tuple(other)


class DataBatch:
    """reference ``include/mxnet/io.h`` DataBatch / io.py"""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label if label is not None else []
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """reference ``io.py:126``"""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()

    # -- iterator-state protocol (preemption-tolerant fit) ----------------
    def state_dict(self):
        """JSON-able mid-epoch position of this iterator.  Restoring it
        with :meth:`load_state_dict` on a freshly-constructed equivalent
        iterator makes ``next()`` yield exactly the batch that would
        have come next — the contract ``Module.fit``'s exact mid-epoch
        resume builds on (docs/resilience.md).  Iterators without the
        protocol raise; fit then degrades to epoch-boundary resume."""
        raise NotImplementedError(
            "%s does not implement the iterator-state protocol "
            "(state_dict/load_state_dict); mid-epoch checkpoint resume "
            "degrades to the epoch boundary" % type(self).__name__)

    def load_state_dict(self, state):
        """Restore a :meth:`state_dict` capture."""
        raise NotImplementedError(
            "%s does not implement the iterator-state protocol"
            % type(self).__name__)


def _init_data(data, allow_empty, default_name):
    """reference io.py _init_data — normalize to list of (name, numpy)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = {}
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """reference ``io.py:453`` — batching/shuffle/pad over in-memory arrays."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        if shuffle:
            idx = np.arange(self.num_data)
            np.random.shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(v[self.cursor:self.cursor + self.batch_size])
                    for _, v in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [array(np.concatenate([v[self.cursor:], v[:pad]], axis=0))
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def state_dict(self):
        # the cursor IS the iterator state: shuffle/discard permute the
        # backing arrays at construction, so an equivalently-constructed
        # iterator (same data/seed) + cursor lands on the same batch
        return {"type": "NDArrayIter", "cursor": self.cursor,
                "num_data": self.num_data, "batch_size": self.batch_size}

    def load_state_dict(self, state):
        if state.get("type", "NDArrayIter") != "NDArrayIter":
            raise MXNetError("iterator state of type %r cannot restore "
                             "onto NDArrayIter" % (state.get("type"),))
        if state.get("num_data", self.num_data) != self.num_data or \
                state.get("batch_size", self.batch_size) != self.batch_size:
            raise MXNetError(
                "NDArrayIter state (num_data=%s, batch_size=%s) does not "
                "match this iterator (num_data=%d, batch_size=%d)"
                % (state.get("num_data"), state.get("batch_size"),
                   self.num_data, self.batch_size))
        self.cursor = int(state["cursor"])


def _read_idx(path):
    """Read an MNIST idx file (gz or raw) — ``src/io/iter_mnist.cc`` format."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


class MNISTIter(DataIter):
    """reference ``src/io/iter_mnist.cc:241`` — idx reader with shuffle and
    distributed sharding (part_index/num_parts)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, seed=0, silent=False,
                 num_parts=1, part_index=0, **kwargs):
        super().__init__(batch_size)
        img = _read_idx(image).astype(np.float32) / 255.0
        lab = _read_idx(label).astype(np.float32)
        if shuffle:
            rs = np.random.RandomState(seed)
            idx = rs.permutation(img.shape[0])
            img, lab = img[idx], lab[idx]
        # distributed shard (reference partitions by part_index/num_parts)
        n = img.shape[0] // num_parts
        img = img[part_index * n:(part_index + 1) * n]
        lab = lab[part_index * n:(part_index + 1) * n]
        img = img.reshape(img.shape[0], -1) if flat \
            else img.reshape(img.shape[0], 1, img.shape[1], img.shape[2])
        self._inner = NDArrayIter(
            {"data": img}, {"softmax_label": lab}, batch_size,
            shuffle=False, last_batch_handle="discard")

    provide_data = property(lambda s: s._inner.provide_data)
    provide_label = property(lambda s: s._inner.provide_label)

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def state_dict(self):
        return {"type": "MNISTIter", "inner": self._inner.state_dict()}

    def load_state_dict(self, state):
        self._inner.load_state_dict(state["inner"])


class CSVIter(DataIter):
    """reference ``src/io/iter_csv.cc:132``"""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32,
                          ndmin=2).reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((data.shape[0],), dtype=np.float32)
        self._inner = NDArrayIter(
            {"data": data}, {"label": label}, batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label")

    provide_data = property(lambda s: s._inner.provide_data)
    provide_label = property(lambda s: s._inner.provide_label)

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def state_dict(self):
        return {"type": "CSVIter", "inner": self._inner.state_dict()}

    def load_state_dict(self, state):
        self._inner.load_state_dict(state["inner"])


class ResizeIter(DataIter):
    """reference ``io.py:216`` — resize an iterator to a fixed #batches."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def state_dict(self):
        return {"type": "ResizeIter", "cur": self.cur,
                "inner": self.data_iter.state_dict()}

    def load_state_dict(self, state):
        self.cur = int(state["cur"])
        self.data_iter.load_state_dict(state["inner"])


class PrefetchingIter(DataIter):
    """reference ``io.py:281`` — background thread double-buffering (the
    python analog of ``src/io/iter_prefetcher.h:49``).

    Owns one daemon thread per sub-iterator; call :meth:`close` (or use
    the iterator as a context manager) to stop and join them — relying
    on ``__del__`` alone leaks N live threads for as long as the GC
    defers the collection."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]
        self._errors = [None for _ in range(self.n_iter)]
        # iterator-state protocol: each produce first captures the
        # sub-iterator's PRE-batch state, so state_dict() can report the
        # position of the buffered batch the consumer has not seen yet
        self._capture_state = True
        self._pending_state = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self._produce(i)
                except StopIteration:
                    self.next_batch[i] = None
                except BaseException as e:  # noqa: BLE001
                    # surface producer crashes on the consumer thread —
                    # swallowing them would deadlock iter_next's wait
                    self._errors[i] = e
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.start()

    def _produce(self, i):
        """Produce sub-iterator ``i``'s next batch — runs ON the prefetch
        thread.  Captures the inner iterator's pre-batch state first
        (see :meth:`state_dict`); the hook :meth:`_produce_batch` is what
        :class:`DevicePrefetchIter` overrides to add the host→device
        copy to the background work."""
        if self._capture_state:
            try:
                self._pending_state[i] = self.iters[i].state_dict()
            except NotImplementedError:
                # the inner iterator has no state protocol: stop asking
                # (once per wrapper, not once per batch)
                self._capture_state = False
                self._pending_state = None
        return self._produce_batch(i)

    def _produce_batch(self, i):
        return self.iters[i].next()

    def state_dict(self):
        """State of the *consumer* position: the producers are drained
        (parked on ``data_taken``) and the captured pre-batch state of
        the buffered batch is returned — restoring it re-produces that
        buffered (never-consumed) batch first, so a wrapper snapshot
        taken after fit consumed ``k`` batches resumes at batch
        ``k + 1`` exactly, prefetch depth and all."""
        self.drain()
        if not self._capture_state or self._pending_state is None \
                or any(s is None for s in self._pending_state):
            raise NotImplementedError(
                "%s cannot snapshot: wrapped iterator(s) lack the "
                "state protocol" % type(self).__name__)
        return {"type": type(self).__name__,
                "inner": [dict(s) for s in self._pending_state]}

    def drain(self):
        """Block until every in-flight produce completes and the
        producer threads are parked (on ``data_taken``).  The inner
        iterators are then safe to mutate externally — e.g. an elastic
        reshard — until ``next()``/``reset()``/``load_state_dict``
        re-arms production."""
        for e in self.data_ready:
            e.wait()

    def load_state_dict(self, state):
        """Restore: park the producers, rewind the inner iterators to
        the captured positions, drop the stale buffered batches, and
        re-arm — the next produced batch comes from the restored
        state."""
        inner = state["inner"]
        if len(inner) != self.n_iter:
            raise MXNetError(
                "prefetch state has %d sub-iterators, wrapper has %d"
                % (len(inner), self.n_iter))
        self.drain()
        for i in range(self.n_iter):
            self.iters[i].load_state_dict(inner[i])
        self._errors = [None for _ in range(self.n_iter)]
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def close(self):
        """Stop the prefetch threads and JOIN them (idempotent).  After
        ``close()`` the iterator must not be used again."""
        self.started = False
        for e in self.data_taken:
            e.set()
        for t in self.prefetch_threads:
            if t is not threading.current_thread():
                t.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: broad-except — interpreter-shutdown GC
            pass

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        self.drain()
        for i in self.iters:
            i.reset()
        # stale producer errors must not outlive the reset
        self._errors = [None for _ in range(self.n_iter)]
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        err = next((e for e in self._errors if e is not None), None)
        if err is not None:
            # clear EVERY producer's error (a stale sibling error must not
            # poison the next, clean round), invalidate the half-populated
            # batches, and re-arm the producers so a caller that catches
            # the error can keep iterating
            self._errors = [None for _ in range(self.n_iter)]
            for j in range(self.n_iter):
                self.next_batch[j] = None
                self.data_ready[j].clear()
                self.data_taken[j].set()
            raise err
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iters"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Different pad number in the data batches"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class DevicePrefetchIter(PrefetchingIter):
    """Device-side double-buffered prefetch: the background thread also
    runs each batch array's ``jax.device_put``, so the host→device copy
    of batch N overlaps the device compute of batch N-1 — completing on
    the device link what :class:`PrefetchingIter` does for host decode
    (``iter_prefetcher.h`` took decode off the critical path; the H2D
    copy stayed on it until now).

    ``placer(name, array) -> NDArray`` does the placement; ``Module``
    passes its ``_device_put_batch``, which recomputes the module's
    mesh sharding per input — on a mesh-bound module (``context=Mesh``
    or ``fit(kvstore='mesh')``) the batch lands pre-sharded over the
    data axis, never on one device with the step left to re-lay it
    out.  Alternatively pass ``device`` (a jax device) or ``sharding``
    (a ``jax.sharding.Sharding``, e.g. a mesh ``NamedSharding``) for a
    module-free placement target.  ``fit(prefetch_to_device=True)``
    (or ``MXNET_DEVICE_PREFETCH=1``) wires this in around
    ``train_data`` and closes it deterministically.
    """

    def __init__(self, iters, placer=None, device=None, sharding=None,
                 rename_data=None, rename_label=None):
        if placer is None:
            target = sharding if sharding is not None else device
            if target is None:
                raise MXNetError("DevicePrefetchIter needs a placer, a "
                                 "device, or a sharding")

            def placer(_name, arr):
                import jax

                from .ndarray import NDArray

                raw = arr._transfer_src() if isinstance(arr, NDArray) \
                    else np.asarray(arr)
                return NDArray._from_jax(jax.device_put(raw, target))

        # set before super().__init__: the prefetch threads start inside
        # it and call _produce immediately
        self._placer = placer
        self._names_cache = {}
        super().__init__(iters, rename_data=rename_data,
                         rename_label=rename_label)

    def _names(self, i):
        cached = self._names_cache.get(i)
        if cached is None:
            cached = ([d.name for d in self.iters[i].provide_data],
                      [d.name for d in self.iters[i].provide_label])
            self._names_cache[i] = cached
        return cached

    def _produce_batch(self, i):
        batch = self.iters[i].next()
        data_names, label_names = self._names(i)
        batch.data = [self._placer(n, a)
                      for n, a in zip(data_names, batch.data)]
        if batch.label:
            batch.label = [self._placer(n, a)
                           for n, a in zip(label_names, batch.label)]
        return batch


class ElasticShardIter(DataIter):
    """Elastic sharded data service (docs/resilience.md "Elastic
    membership & resharding"): serves this worker's deterministic shard
    of a record-addressable dataset, recomputes shard ownership on
    membership change, and carries a **global sample-accounting ledger**
    so an elasticity event neither skips nor repeats records.

    Sharding is a pure function: the records *remaining* in the current
    data epoch (all minus the ledger) are partitioned by
    :func:`mxnet_tpu.elastic.shard_records` over ``(sorted ranks,
    membership epoch)`` — every member computes the identical partition,
    and all members serve the same number of batches per assignment
    (short shards wrap-pad their tail batch; pad slots are presentation
    copies, excluded from the ledger).

    The ledger is *derivable*: because synchronous training keeps ranks
    in batch lockstep, the globally-consumed set at cursor ``pos`` is
    ``base ∪ (every rank's first pos batches of its shard)`` — a pure
    function of the state dict, with no runtime cross-worker union.  Any
    one rank's snapshot therefore carries the correct GLOBAL ledger for
    its boundary, which is exactly what the reshard cycle adopts when it
    rolls every member back to the newest snapshot generation.

    Sources: in-memory arrays (``data``/``label``, NDArrayIter-style) or
    ``record_reader`` — a callable ``(ids) -> (data_arrays,
    label_arrays)`` over e.g. an ``MXIndexedRecordIO`` file — with
    ``num_records``.
    """

    def __init__(self, data=None, label=None, batch_size=1, rank=0,
                 ranks=(0,), membership_epoch=0, record_reader=None,
                 num_records=None, data_name="data",
                 label_name="softmax_label", audit=False):
        super().__init__(batch_size)
        self._lock = threading.Lock()
        self.audit = bool(audit)
        if record_reader is not None:
            if num_records is None:
                raise MXNetError(
                    "ElasticShardIter(record_reader=...) needs "
                    "num_records")
            self._reader = record_reader
            self._n = int(num_records)
            probe_d, probe_l = record_reader([0])

            def _descs(arrays, default):
                names = [default] if len(arrays) == 1 else \
                    ["_%d_%s" % (i, default) for i in range(len(arrays))]
                return [DataDesc(nm,
                                 (batch_size,) + np.asarray(a).shape[1:],
                                 np.asarray(a).dtype)
                        for nm, a in zip(names, arrays)]

            self._data_descs = _descs(probe_d, data_name)
            self._label_descs = _descs(probe_l, label_name)
            self._arrays = None
        else:
            self._reader = None
            self._arrays = (_init_data(data, allow_empty=False,
                                       default_name=data_name),
                            _init_data(label, allow_empty=True,
                                       default_name=label_name))
            self._n = self._arrays[0][0][1].shape[0]
            self._data_descs = [
                DataDesc(k, (batch_size,) + v.shape[1:], v.dtype)
                for k, v in self._arrays[0]]
            self._label_descs = [
                DataDesc(k, (batch_size,) + v.shape[1:], v.dtype)
                for k, v in self._arrays[1]]
        if self._n < 1:
            raise MXNetError("ElasticShardIter: empty dataset")
        self.rank = rank
        self.ranks = sorted(ranks)
        self.membership_epoch = int(membership_epoch)
        self.data_epoch = 0
        self.base = set()        # global ledger at this assignment's start
        self._pos = 0            # batches served under this assignment
        self._committed = {}     # data_epoch -> ids THIS rank committed
        # sample-accounting ledger: the reshard machinery only ever
        # reads the current and previous data epoch, so reset() prunes
        # older epochs by default — ``audit=True`` keeps the whole-job
        # trail (the chaos/acceptance tests assert exactly-once over
        # EVERY epoch of a run)
        self.applied = {}        # data_epoch -> {id: surviving-train count}
        self.history = []        # closed assignment segments (diagnostics)
        with self._lock:
            self._recompute()

    # -- pure shard/ledger math (lock held) -------------------------------
    def _recompute(self):
        from .elastic import shard_records

        remaining = [i for i in range(self._n) if i not in self.base]
        if remaining:
            self._parts = shard_records(remaining, self.ranks,
                                        self.membership_epoch)
        else:
            self._parts = {r: [] for r in self.ranks}
        self._owned = list(self._parts.get(self.rank, []))
        longest = max((len(p) for p in self._parts.values()), default=0)
        self._nbatches = -(-longest // self.batch_size) if longest else 0

    def _served_global(self, pos):
        """The ledger at cursor ``pos`` of THIS assignment: ``base`` plus
        every rank's first ``pos`` batches of its shard (lockstep makes
        all ranks' cursors equal at any sync boundary)."""
        out = set(self.base)
        take = pos * self.batch_size
        for part in self._parts.values():
            out.update(part[:take])
        return out

    def ledger(self):
        """The global sample-accounting ledger at this worker's cursor:
        the set of records of the current data epoch whose updates are
        part of the surviving trajectory."""
        with self._lock:
            return self._served_global(self._pos)

    @property
    def num_records(self):
        return self._n

    # -- DataIter protocol -------------------------------------------------
    @property
    def provide_data(self):
        return self._data_descs

    @property
    def provide_label(self):
        return self._label_descs

    def _read(self, ids):
        from .ndarray import array as _array

        if self._reader is not None:
            data, label = self._reader(ids)
            return ([_array(np.asarray(a)) for a in data],
                    [_array(np.asarray(a)) for a in label])
        data_src, label_src = self._arrays
        idx = np.asarray(ids, np.int64)
        return ([_array(v[idx]) for _k, v in data_src],
                [_array(v[idx]) for _k, v in label_src])

    def next(self):
        with self._lock:
            if self._pos >= self._nbatches:
                raise StopIteration
            own = self._owned
            start = self._pos * self.batch_size
            ids = list(own[start:start + self.batch_size])
            pad = self.batch_size - len(ids)
            if pad:
                src = own
                if not src:
                    # an empty shard (fewer remaining records than
                    # ranks after a late-epoch reshard): serve full-pad
                    # batches from the lowest remaining record so this
                    # rank stays in the sync-round lockstep its peers
                    # depend on; pads never commit to the ledger.
                    # _nbatches > 0 guarantees some part is non-empty.
                    src = [min(min(p)
                              for p in self._parts.values() if p)]
                k = 0
                while len(ids) < self.batch_size:
                    ids.append(src[k % len(src)])
                    k += 1
            self._pos += 1
        data, label = self._read(ids)
        return DataBatch(data=data, label=label, pad=pad,
                         index=np.asarray(ids, np.int64))

    def reset(self):
        """Data-epoch boundary: close the current assignment segment and
        start a fresh pass over the FULL record set under the current
        membership."""
        with self._lock:
            self._close_segment("epoch-end")
            self.data_epoch += 1
            self.base = set()
            self._pos = 0
            # sync lockstep keeps rank cursors within one batch, so the
            # rollback target (the newest snapshot generation) is always
            # in the current or previous data epoch: older commit sets
            # can never be retracted and would otherwise grow without
            # bound over a long job
            for e in [e for e in self._committed
                      if e < self.data_epoch - 1]:
                del self._committed[e]
            if not self.audit:
                # same rule as _committed: epochs older than the
                # rollback horizon can never be retracted — dropping
                # them bounds the ledger at O(records) instead of
                # O(records x epochs) over a long job
                for e in [e for e in self.applied
                          if e < self.data_epoch - 1]:
                    del self.applied[e]
            self._recompute()

    def _close_segment(self, why):
        self.history.append({
            "why": why, "data_epoch": self.data_epoch,
            "membership_epoch": self.membership_epoch,
            "ranks": list(self.ranks), "pos": self._pos,
            "covered": len(self._served_global(self._pos))})

    # -- ledger commits ----------------------------------------------------
    def commit(self, index, pad=0):
        """Record a trained batch's non-pad ids as applied in the
        surviving trajectory.  ``fit(elastic=True)`` calls this after
        ``update()`` landed; a batch whose update was rejected with
        ``StaleEpoch`` is never committed, and commits rolled back by a
        reshard are retracted in :meth:`reshard`."""
        ids = np.asarray(index).ravel()
        if pad:
            ids = ids[:len(ids) - pad]
        with self._lock:
            c = self._committed.setdefault(self.data_epoch, set())
            a = self.applied.setdefault(self.data_epoch, {})
            for i in ids:
                i = int(i)
                if i in c:
                    continue  # pad wrap / replay: counted once
                c.add(i)
                a[i] = a.get(i, 0) + 1

    def _retract(self, epoch, rolled):
        """Undo rolled-back commits in the epoch's ledger (lock held):
        decrement each record's applied count (dropping zeroed entries)
        and remove it from the committed set, so the records re-enter
        the remaining pool at the next :meth:`_recompute`."""
        a = self.applied.setdefault(epoch, {})
        for i in rolled:
            n = a.get(i, 0) - 1
            if n > 0:
                a[i] = n
            else:
                a.pop(i, None)
        self._committed.get(epoch, set()).difference_update(rolled)

    # -- elastic reshard ---------------------------------------------------
    def reshard(self, rank, ranks, membership_epoch, state=None):
        """Recompute shard ownership for a new membership.  With
        ``state`` (the adopted snapshot's iterator state) the GLOBAL
        ledger rolls back/forward to that snapshot's boundary first:
        records the snapshot had not yet accounted return to the
        remaining pool (their updates were rolled back with the
        parameters), and this rank's local commits beyond the boundary
        are retracted — no record is skipped, none is trained twice in
        the surviving trajectory."""
        from .elastic import shard_records

        with self._lock:
            self._close_segment("reshard")
            if state is not None:
                s_ranks = sorted(state["ranks"])
                s_base = set(int(i) for i in state["base"])
                s_pos = int(state["pos"])
                s_bs = int(state.get("batch_size", self.batch_size))
                s_depoch = int(state["data_epoch"])
                remaining = [i for i in range(self._n) if i not in s_base]
                parts = shard_records(remaining, s_ranks,
                                      int(state["membership_epoch"])) \
                    if remaining else {}
                new_base = set(s_base)
                for part in parts.values():
                    new_base.update(part[:s_pos * s_bs])
                # retract local commits the rollback undid
                for epoch in sorted(self._committed):
                    if epoch < s_depoch:
                        continue
                    c = self._committed[epoch]
                    self._retract(
                        epoch, c - new_base if epoch == s_depoch else set(c))
                self.data_epoch = s_depoch
                self.base = new_base
            else:
                # no snapshot generation exists (a fresh job's initial
                # sync, or a membership change before the leader's first
                # write landed): there is no common rollback target, so
                # the SEGMENT START is the rollback target — the base is
                # kept and the current assignment's local commits are
                # retracted, giving every member (newcomers included)
                # the identical remaining pool.  Per-rank committed
                # views must NOT leak into the base: a pull racing the
                # epoch bump leaves ranks with different committed
                # boundaries, and divergent bases mean divergent shard
                # ownership.  An update that landed without a generation
                # (at most the segment's first round under the pinned
                # every-batch elastic cadence) is retrained rather than
                # divergently skipped.
                c = self._committed.get(self.data_epoch, set())
                self._retract(self.data_epoch, c - self.base)
            self.rank = rank
            self.ranks = sorted(ranks)
            self.membership_epoch = int(membership_epoch)
            self._pos = 0
            self._recompute()

    # -- iterator-state protocol (PR 5) ------------------------------------
    def state_dict(self):
        with self._lock:
            return {"type": "ElasticShardIter", "num_records": self._n,
                    "batch_size": self.batch_size,
                    "data_epoch": self.data_epoch,
                    "membership_epoch": self.membership_epoch,
                    "ranks": list(self.ranks), "rank": self.rank,
                    "pos": self._pos, "base": sorted(self.base)}

    def load_state_dict(self, state):
        if state.get("type") != "ElasticShardIter":
            raise MXNetError("iterator state of type %r cannot restore "
                             "onto ElasticShardIter" % (state.get("type"),))
        if int(state.get("num_records", self._n)) != self._n or \
                int(state.get("batch_size", self.batch_size)) \
                != self.batch_size:
            raise MXNetError(
                "ElasticShardIter state (num_records=%s, batch_size=%s) "
                "does not match this iterator (num_records=%d, "
                "batch_size=%d)" % (state.get("num_records"),
                                    state.get("batch_size"), self._n,
                                    self.batch_size))
        with self._lock:
            self.data_epoch = int(state["data_epoch"])
            self.membership_epoch = int(state["membership_epoch"])
            self.ranks = sorted(state["ranks"])
            # restore the captured rank too (found by the state-protocol
            # lint pass: the key was emitted but silently dropped) — a
            # same-rank resume is a no-op, but restoring a capture onto
            # a differently-constructed iterator must land on the SAME
            # shard assignment the capture described, or _recompute()
            # below walks another rank's records
            self.rank = int(state.get("rank", self.rank))
            self.base = set(int(i) for i in state["base"])
            self._pos = int(state["pos"])
            self._recompute()


def _mp_decode_worker(ctor_kwargs, shm_names, data_shape, label_shape,
                      cmd_q, free_q, out_q):
    """Decode worker PROCESS: owns one dataset shard
    (part_index/num_parts inside ctor_kwargs) and runs the full native
    decode pipeline on it, one epoch per 'epoch' command.  Runs under
    the 'spawn' start method so the child gets a fresh interpreter (a
    forked child would inherit the parent's initialized XLA runtime,
    whose threads do not survive fork) — and, decisively for the 1-core
    clamp, its OWN CPU affinity mask: the decode library sizes its pool
    from sched_getaffinity, so N processes on an M-core host scale
    where in-process threads clamp to the parent's mask.

    Batches hand over through SHARED-MEMORY slots, not pickled queues:
    a 224-ImageNet f32 batch is ~77 MB, and pickling it through
    mp.Queue's feeder thread measured 5x slower than the decode itself.
    The worker memcpys into a free slot and sends only the slot index;
    the parent memcpys out and returns the slot via free_q."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from multiprocessing import shared_memory

    import numpy as np

    from mxnet_tpu.image import ImageIter

    shms = [shared_memory.SharedMemory(name=n) for n in shm_names]
    data_n = int(np.prod(data_shape)) * 4
    views = [(np.ndarray(data_shape, np.float32, buffer=s.buf),
              np.ndarray(label_shape, np.float32, buffer=s.buf,
                         offset=data_n)) for s in shms]
    from mxnet_tpu.native import get_imgdecode_lib

    if get_imgdecode_lib() is None:
        # no native decode in this environment: swap native_norm for the
        # equivalent python batch converter so the fallback still
        # normalizes (silently un-normalized data would train garbage)
        mean, std, scale = ctor_kwargs.pop("native_norm")
        ctor_kwargs["post_batch"] = _batch_converter(
            np.asarray(mean, np.float32), np.asarray(std, np.float32),
            scale, None)
    it = ImageIter(**ctor_kwargs)
    while True:
        cmd = cmd_q.get()
        if cmd == "stop":
            break
        it.reset()
        while True:
            slot = free_q.get()   # claim the slot BEFORE decoding
            if slot is None:      # abort sentinel (parent close())
                break
            dv, lv = views[slot]
            it.batch_out = (dv, lv)   # native path decodes into the slot
            try:
                batch = it.next()
            except StopIteration:
                free_q.put(slot)
                break
            if it.batch_out is not None:
                # non-native fallback didn't consume the buffers — copy
                it.batch_out = None
                np.copyto(dv, batch.data[0].asnumpy())
                lab = batch.label[0].asnumpy().astype(np.float32)
                np.copyto(lv, lab.reshape(label_shape))
            out_q.put(("b", slot, batch.pad))
        out_q.put(("end", -1, 0))
    for s in shms:
        s.close()


class MultiProcessIter(DataIter):
    """Host-sharded multi-process decode (round-4/5 IO-scaling design).

    N worker PROCESSES each own a 1/N dataset shard via the existing
    ``part_index``/``num_parts`` sharding and run the one-C-call decode
    pipeline; finished batches return over bounded per-worker queues and
    the parent round-robins them.  This is the multi-worker analog of
    the reference's decode-thread pool (``iter_image_recordio.cc:458``)
    for hosts where in-process threads cannot scale: the decode library
    clamps its pool to the process affinity mask, and separate processes
    each carry their own mask (plus their own GIL).

    Epoch semantics: each worker's shard pads/rolls independently, so
    batch ORDER differs from the single-process iterator but per-epoch
    sample coverage is identical (the sharding is the same
    ``part_index``/``num_parts`` split dist training uses).  Batches
    cross the process boundary through per-worker shared-memory slot
    rings — one memcpy in, one memcpy out, slot indices on the queues —
    because pickling 77 MB f32 batches through mp.Queue measured 5x
    slower than the decode work itself.
    """

    def __init__(self, ctor_kwargs, num_procs, batch_size, data_shape,
                 label_width=1, data_name="data",
                 label_name="softmax_label", slots_per_worker=2):
        import multiprocessing as mp
        from multiprocessing import shared_memory

        super().__init__(batch_size)
        self._data_shape = tuple(data_shape)
        self._label_width = label_width
        self._data_name, self._label_name = data_name, label_name
        full_data = (batch_size,) + self._data_shape
        label_shape = (batch_size, label_width)
        data_n = int(np.prod(full_data)) * 4
        label_n = int(np.prod(label_shape)) * 4
        ctx = mp.get_context("spawn")
        self._workers, self._cmd_qs, self._out_qs = [], [], []
        self._free_qs, self._shms, self._views = [], [], []
        for w in range(num_procs):
            kw = dict(ctor_kwargs, part_index=w, num_parts=num_procs)
            cmd_q = ctx.Queue()
            free_q = ctx.Queue()
            out_q = ctx.Queue()
            shms = [shared_memory.SharedMemory(
                create=True, size=data_n + label_n)
                for _ in range(slots_per_worker)]
            self._views.append([
                (np.ndarray(full_data, np.float32, buffer=s.buf),
                 np.ndarray(label_shape, np.float32, buffer=s.buf,
                            offset=data_n)) for s in shms])
            for i in range(slots_per_worker):
                free_q.put(i)
            p = ctx.Process(target=_mp_decode_worker,
                            args=(kw, [s.name for s in shms], full_data,
                                  label_shape, cmd_q, free_q, out_q),
                            daemon=True)
            p.start()
            self._workers.append(p)
            self._cmd_qs.append(cmd_q)
            self._free_qs.append(free_q)
            self._out_qs.append(out_q)
            self._shms.append(shms)
        self._live = []
        self._rr = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._label_width == 1 \
            else (self.batch_size, self._label_width)
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        # drain any tail of the previous epoch (returning its slots) so
        # commands stay in phase.  NOTE: a mid-epoch reset waits for the
        # workers to decode the REST of their shards (batches
        # discarded); epoch-boundary resets, the training-loop norm,
        # cost nothing
        for w, q in enumerate(self._out_qs):
            if w in getattr(self, "_live", []):
                while True:
                    kind, slot, _pad = q.get()
                    if kind == "end":
                        break
                    self._free_qs[w].put(slot)
        for q in self._cmd_qs:
            q.put("epoch")
        self._live = list(range(len(self._workers)))
        self._rr = 0

    def next(self):
        while self._live:
            w = self._live[self._rr % len(self._live)]
            kind, slot, pad = self._out_qs[w].get()
            if kind == "end":
                self._live.remove(w)
                continue
            dv, lv = self._views[w][slot]
            # ONE memcpy out of the slot into a fresh per-batch buffer.
            # Zero-copy handoff was measured and REVERTED: the executor
            # device_puts host batches by aliasing (jax CPU backend
            # zero-copy), so a recycled slot corrupts the async
            # in-flight step — a fresh buffer has the same lifetime
            # semantics as the in-process iterator (GC-owned by the
            # returned NDArray).  The copy overlaps worker decode on
            # any multi-core host.
            data = np.array(dv)
            label = np.array(lv)
            self._free_qs[w].put(slot)  # copied out — recycle now
            if self._label_width == 1:
                label = label.reshape(self.batch_size)
            self._rr += 1
            from . import ndarray as _nd

            return DataBatch(data=[_nd.from_host(data)],
                             label=[_nd.from_host(label)],
                             pad=pad,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)
        raise StopIteration

    def close(self):
        # wake any worker blocked on free_q (abort sentinel) so the
        # 'stop' command is reachable — otherwise a mid-epoch close
        # hangs the join and falls back to SIGTERM
        for q in self._free_qs:
            try:
                q.put(None)
            except (OSError, ValueError):
                pass
        for q in self._cmd_qs:
            try:
                q.put("stop")
            except (OSError, ValueError):
                pass
        for p in self._workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._workers = []
        for shms in self._shms:
            for s in shms:
                try:
                    s.close()
                    s.unlink()
                except (FileNotFoundError, OSError):
                    pass
        self._shms = []

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: broad-except — interpreter-shutdown GC
            pass


def corrupt_skip_count():
    """Process-wide count of corrupt records skipped by the data pipeline
    under ``MXNET_IO_SKIP_CORRUPT=1`` (see docs/resilience.md).  Per-reader
    counts live on ``MXRecordIO.num_skipped``."""
    from . import recordio

    return recordio.skipped_record_count()


def reset_corrupt_skip_count():
    from . import recordio

    recordio.reset_skipped_record_count()


def _batch_converter(mean, std, scale, ctx):
    """Batch-level cast+normalize+transpose for the ImageRecordIter fast
    path: uint8 HWC staging -> f32 NCHW, either host-vectorized or — with
    ``ctx`` on an accelerator — ON DEVICE, so the host ships a quarter of
    the bytes and the chip does the layout work (the TPU answer to the
    reference's GPU-side ``ImageRecordUInt8Iter`` pattern)."""
    from . import ndarray

    use_mean = mean is not None and mean.any()
    use_std = std is not None and (std != 1.0).any()

    if ctx is not None:
        import jax
        import jax.numpy as jnp

        dev = ctx.jax_device()
        mean_j = jnp.asarray(mean) if use_mean else None
        std_j = jnp.asarray(std) if use_std else None

        @jax.jit
        def convert(x):
            y = x.astype(jnp.float32)
            if use_mean:
                y = y - mean_j
            if use_std:
                y = y / std_j
            if scale != 1.0:
                y = y * jnp.float32(scale)
            return y.transpose(0, 3, 1, 2)

        def post(hwc, label):
            out = convert(jax.device_put(hwc, dev))
            return (ndarray.NDArray._from_jax(out, ctx),
                    ndarray.array(label, ctx=ctx))

        return post

    mean_c = mean.reshape(1, -1, 1, 1) if use_mean else None
    std_c = std.reshape(1, -1, 1, 1) if use_std else None
    from .context import cpu as _cpu

    def post(hwc, label):
        # ONE strided-read/contiguous-write pass does transpose+cast; the
        # resulting contiguous buffer makes the jax conversion a memcpy.
        # Host batches stay on CPU (reference iterators fill pinned host
        # memory; the executor's _load_io does the device copy) — an
        # accelerator default context would drag every batch through the
        # host->device link twice
        x = hwc.transpose(0, 3, 1, 2).astype(np.float32)
        if use_mean:
            x -= mean_c
        if use_std:
            x /= std_c
        if scale != 1.0:
            x *= np.float32(scale)
        return (ndarray.array(x, ctx=_cpu()),
                ndarray.array(label, ctx=_cpu()))

    return post


def ImageRecordIter(path_imgrec, data_shape, batch_size, shuffle=False,
                    part_index=0, num_parts=1, rand_crop=False,
                    rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                    std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0, resize=0,
                    path_imgidx=None, prefetch=True, data_name="data",
                    label_name="softmax_label", label_width=1,
                    preprocess_threads=4, prefetch_buffer=1,
                    round_batch=True, ctx=None, decode_procs=None,
                    **kwargs):
    """C-iter-style facade over ``image.ImageIter`` (+ prefetch thread).

    Reference: ``ImageRecordIter`` registered at
    ``src/io/iter_image_recordio.cc:458`` with the decode→augment→batch→
    prefetch decorator chain of §3.5; kwargs mirror its dmlc params
    (``mean_r``..., ``rand_crop``, ``part_index``/``num_parts``...).

    TPU-first pipeline shape: N decode threads (``preprocess_threads``,
    default 4 — cv2 releases the GIL) run geometric augmenters on uint8,
    the batch is cast/normalized/transposed ONCE (on ``ctx`` when it is
    an accelerator — quarter the host->device bytes, layout work on the
    MXU's neighbors), and ``PrefetchingIter`` double-buffers the whole
    thing against the consumer (``iter_prefetcher.h:49`` analog).
    Per-image color augmentations (brightness/contrast/saturation/pca)
    need float images, so requesting them falls back to the reference's
    per-image CastAug chain.

    ``decode_procs`` (default ``$MXNET_DECODE_PROCS`` or 0): when > 1,
    decode runs in that many worker PROCESSES instead of in-process
    threads (``MultiProcessIter``) — the scaling path for hosts where
    the decode pool clamps to a narrow affinity mask.  Requires the
    fast path (no color augs) and is mutually exclusive with
    ``num_parts`` sharding (the processes ARE the parts).
    """
    from .image import (CenterCropAug, CreateAugmenter, HorizontalFlipAug,
                        ImageIter, RandomCropAug, ResizeAug)

    known = ("brightness", "contrast", "saturation", "pca_noise",
             "inter_method")
    unknown = set(kwargs) - set(known)
    if unknown:
        raise TypeError("ImageRecordIter: unsupported parameters %s"
                        % sorted(unknown))
    mean = np.array([mean_r, mean_g, mean_b], np.float32)
    std = np.array([std_r, std_g, std_b], np.float32)
    color_ops = any(kwargs.get(k) for k in
                    ("brightness", "contrast", "saturation", "pca_noise"))
    post_batch = None
    if not color_ops:
        # fast path: geometric augs stay uint8; one batch-level convert
        inter = kwargs.get("inter_method", 1)
        aug_list = []
        if resize > 0:
            aug_list.append(ResizeAug(resize, inter))
        crop_size = (data_shape[2], data_shape[1])
        aug_list.append(RandomCropAug(crop_size, inter) if rand_crop
                        else CenterCropAug(crop_size, inter))
        if rand_mirror:
            aug_list.append(HorizontalFlipAug(0.5))
        post_batch = _batch_converter(mean, std, scale, ctx)
    else:
        aug_list = CreateAugmenter(
            data_shape, resize=resize, rand_crop=rand_crop,
            rand_mirror=rand_mirror,
            mean=mean if mean.any() else None,
            std=std if (std != 1.0).any() else None,
            **kwargs)
        if scale != 1.0:
            aug_list.append(lambda img: img * scale)
    # host-destination batches fuse cast+normalize+transpose into the
    # native decode call (f32 NCHW straight out of C); device batches
    # keep uint8 staging so the link carries a quarter of the bytes
    native_norm = (tuple(mean), tuple(std), float(scale)) \
        if (post_batch is not None and ctx is None) else None
    # reference round_batch=1 (iter_batchloader.h:36): the final partial
    # batch wraps around to the start of the data and the next epoch
    # skips the wrapped samples — every sample still appears once per
    # cycle and every batch is full (pad == 0), the semantics dist
    # workers rely on for equal step counts.  Defaults ON to match the
    # reference (iter_batchloader.h:30 set_default(true)); round_batch=0
    # keeps the pad-and-set-batch.pad behavior.
    if decode_procs is None:
        decode_procs = int(os.environ.get("MXNET_DECODE_PROCS", "0"))
    if decode_procs > 1:
        if color_ops:
            raise ValueError("decode_procs needs the fast (geometric-"
                             "aug) path; color augs run in-process")
        if num_parts != 1:
            raise ValueError("decode_procs and num_parts are mutually "
                             "exclusive (the processes are the parts)")
        if ctx is not None:
            raise ValueError("decode_procs produces host f32 batches; "
                             "the uint8-on-device conversion path "
                             "(ctx=...) is single-process only")
        ctor = dict(batch_size=batch_size, data_shape=data_shape,
                    label_width=label_width, path_imgrec=path_imgrec,
                    path_imgidx=path_imgidx, shuffle=shuffle,
                    aug_list=aug_list, data_name=data_name,
                    label_name=label_name,
                    preprocess_threads=preprocess_threads,
                    native_norm=(tuple(mean), tuple(std), float(scale)),
                    last_batch_handle="roll_over" if round_batch
                    else "pad")
        return MultiProcessIter(ctor, decode_procs, batch_size,
                                data_shape, label_width=label_width,
                                data_name=data_name,
                                label_name=label_name)
    it = ImageIter(batch_size, data_shape, label_width=label_width,
                   path_imgrec=path_imgrec, path_imgidx=path_imgidx,
                   shuffle=shuffle, part_index=part_index,
                   num_parts=num_parts, aug_list=aug_list,
                   data_name=data_name, label_name=label_name,
                   preprocess_threads=preprocess_threads,
                   post_batch=post_batch, native_norm=native_norm,
                   last_batch_handle="roll_over" if round_batch else "pad")
    if not prefetch or not prefetch_buffer:
        return it
    return PrefetchingIter(it)
