"""Paged KV-cache memory subsystem — block pools, allocator, prefix cache.

The dense :class:`~mxnet_tpu.serving.decode.DecodeEngine` cache reserves
``(S, max_len)`` rows per layer for every slot: worst-case HBM for every
session, so memory — not compute — caps concurrency and context length
(ROADMAP open item 3).  This module is the storage layer that frees the
reservation while keeping the decode step fixed-shape:

* **device side** (owned by the engine): per-layer pools of shape
  ``(num_blocks, block_size, heads, head_dim)``; a session's cache is a
  set of pool rows named by its **block table**, a ``(max_blocks,)``
  int32 row per slot.  Block 0 is the reserved **scratch block**: never
  allocated, it is where unallocated table entries point, so inactive
  slots and bucket padding scatter harmlessly at fixed shape (the
  attention mask keeps scratch garbage unreadable — the same idiom the
  dense cache uses for inactive rows);
* **host side** (this module): a :class:`BlockAllocator` — free list,
  refcounts — plus the per-slot block tables held by
  :class:`KVBlockPool`, and a sha1-keyed :class:`PrefixCache` that lets
  sessions sharing a prompt prefix admit **by reference**: full shared
  blocks are increfed into the new slot's table, a partially-filled
  tail block is **copied on write** at admission (an in-graph block
  copy folded into the paged prefill program — no extra compile, no
  recompute), and only the unshared suffix runs prefill compute.

The allocator is the one piece touched from more than one thread
(engine thread allocates/frees; ``describe``/``/healthz`` read
occupancy), so its free list and refcounts live strictly under its own
lock — the graftlint lock-discipline pass (and a strip-the-lock
mutation test in ``tests/test_graftlint.py``) keep it that way.  Block
tables are engine-thread-only by design and the pool's counters are
monotonic ints (torn reads impossible in CPython), so neither needs the
lock.

Freeing is purely a host-side bookkeeping act: device rows are never
zeroed on free — a recycled block is overwritten by its next owner's
scatter before the mask ever exposes it, exactly like a retired dense
slot.  Ordering is safe because every device program that reads or
writes pool rows threads the donated pool arrays through the engine's
single dispatch chain: a later dispatch that recycles a block depends
on the earlier one that last read it.

Sizing: ``MXNET_KV_BLOCK_SIZE`` tokens per block (default 16) and
``MXNET_KV_BLOCKS`` total blocks per engine (default: dense-equivalent,
``slots * ceil(max_len/block_size) + 1`` so the paged engine can never
be *worse* than dense; size it smaller to oversubscribe and let the
prefix cache + typed :class:`KVBlocksExhausted` admission control do
their job).  ``MXNET_KV_PREFIX_CACHE=0`` disables prefix reuse.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict, deque, namedtuple

import numpy as np

from .. import telemetry as _telemetry
from ..base import MXNetError
from ..compile_cache import _env_int
from .batcher import Overloaded

__all__ = ["KVBlocksExhausted", "BlockAllocator", "PrefixCache",
           "KVBlockPool", "AdmitPlan"]


class KVBlocksExhausted(Overloaded):
    """The block pool cannot serve an allocation even after evicting
    the prefix cache — a typed :class:`Overloaded`, so pools and
    clients shed/retry it exactly like a queue-bound rejection."""


#: admission-time block plan: re-/prefill the transcript suffix from
#: absolute position ``start`` (0 = cold, no shared prefix); when
#: ``cow_dst`` is nonzero the prefill program first copies pool row
#: ``cow_src`` -> ``cow_dst`` (the shared partial tail block) in-graph.
AdmitPlan = namedtuple("AdmitPlan", ["start", "cow_src", "cow_dst",
                                     "prefix_hit", "reused_tokens"])


class BlockAllocator:
    """Host-side free list + refcounts over ``num_blocks`` device rows.

    Block ids are ``1 .. num_blocks-1``; block 0 is the scratch block
    and is never handed out.  A block is freed when its refcount drops
    to zero (sessions and prefix-cache entries each hold one reference
    per table/entry occurrence).  All state lives under ``_lock`` —
    allocation happens on the engine thread but occupancy is read from
    describe/healthz threads.
    """

    def __init__(self, num_blocks, block_size):
        num_blocks = int(num_blocks)
        block_size = int(block_size)
        if block_size < 1:
            raise MXNetError("KV block_size must be >= 1, got %d"
                             % block_size)
        if num_blocks < 2:
            raise MXNetError(
                "KV pool needs >= 2 blocks (block 0 is reserved "
                "scratch), got %d" % num_blocks)
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._lock = threading.Lock()
        self._free = deque(range(1, num_blocks))
        self._ref = {}

    def alloc(self, n):
        """Take ``n`` blocks (refcount 1 each); raises
        :class:`KVBlocksExhausted` — atomically, taking none — when the
        free list is short."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                raise KVBlocksExhausted(
                    "KV pool exhausted: %d blocks requested, %d free of "
                    "%d allocatable" % (n, len(self._free),
                                        self.num_blocks - 1))
            out = [self._free.popleft() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
        return out

    def incref(self, blocks):
        """Add one reference to each (already-allocated) block —
        admit-by-reference and prefix-cache insertion."""
        with self._lock:
            for b in blocks:
                if b not in self._ref:
                    raise MXNetError(
                        "incref of unallocated KV block %d" % int(b))
                self._ref[b] += 1

    def decref(self, blocks):
        """Drop one reference from each block; blocks reaching zero go
        back on the free list.  Returns the freed block ids."""
        freed = []
        with self._lock:
            for b in blocks:
                b = int(b)
                r = self._ref.get(b)
                if r is None:
                    raise MXNetError(
                        "double free of KV block %d" % b)
                if r == 1:
                    del self._ref[b]
                    self._free.append(b)
                    freed.append(b)
                else:
                    self._ref[b] = r - 1
        return freed

    def refcount(self, block):
        with self._lock:
            return self._ref.get(int(block), 0)

    def available(self):
        with self._lock:
            return len(self._free)

    def used(self):
        with self._lock:
            return len(self._ref)

    def reset(self):
        """Forget everything (engine restart after a poisoned dispatch:
        the device pools were rebuilt from zeros, so every host
        reference is moot)."""
        with self._lock:
            self._free = deque(range(1, self.num_blocks))
            self._ref = {}


class PrefixCache:
    """sha1-keyed index from prompt-prefix content to resident blocks.

    Every admitted prompt is indexed at each block-aligned prefix
    length AND at its full length; each entry holds one allocator
    reference per covered block, so retiring the session that produced
    the K/V does NOT free it — later sessions sharing the prefix admit
    against the cached rows.  A lookup matches the longest indexed
    prefix of the new transcript (capped at ``len-1``: the last prompt
    token is always recomputed, its logits seed the first sample).
    Entries are LRU; the pool evicts them when the allocator runs dry,
    so cached prefixes never block live admissions.

    Sharing is safe without copying because shared rows are never
    rewritten: a session writes positions ``>= len(its own prompt)``
    only, and a matched prefix is at most ``len-1 < len(prompt)`` long
    — the one writable overlap (a partially-filled tail block) is
    copied on write at admission by the engine's prefill program.
    """

    def __init__(self, allocator, *, capacity=None, enabled=True):
        self._alloc = allocator
        self.block_size = allocator.block_size
        self.capacity = int(capacity) if capacity is not None \
            else _env_int("MXNET_KV_PREFIX_ENTRIES", 256)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._entries = OrderedDict()   # sha1 -> (length, blocks tuple)
        self.hits = 0
        self.insertions = 0
        self.evictions = 0

    @staticmethod
    def _key(tokens, length):
        return hashlib.sha1(np.ascontiguousarray(
            tokens[:length], dtype=np.int32).tobytes()).hexdigest()

    def lookup(self, tokens):
        """Longest indexed prefix of ``tokens`` (< its full length).
        Returns ``(matched_len, blocks)`` with one reference taken on
        each returned block FOR THE CALLER, or ``(0, [])``."""
        if not self.enabled:
            return 0, []
        n = int(len(tokens))
        top = n - 1
        if top <= 0:
            return 0, []
        bs = self.block_size
        cands = [top]
        for lng in range((top // bs) * bs, 0, -bs):
            if lng != top:
                cands.append(lng)
        for lng in cands:
            key = self._key(tokens, lng)
            with self._lock:
                ent = self._entries.get(key)
                if ent is None or ent[0] != lng:
                    continue
                self._entries.move_to_end(key)
                blocks = list(ent[1])
                # caller's reference, taken under the cache lock so a
                # concurrent eviction cannot free the rows in between
                # (lock order cache -> allocator, one way everywhere)
                self._alloc.incref(blocks)
                self.hits += 1
            return lng, blocks
        return 0, []

    def insert(self, tokens, table_row):
        """Index ``tokens`` (a prompt resident in ``table_row``'s
        blocks) at every block-aligned prefix length plus the full
        length; no-op for lengths already indexed."""
        if not self.enabled:
            return
        n = int(len(tokens))
        if n < 1:
            return
        bs = self.block_size
        # aligned prefixes + the full length (prompt-extension hits) +
        # length n-1 (an IDENTICAL prompt resubmitted hits at n-1: the
        # last token is always recomputed for its first-sample logits,
        # everything before it rides the cache)
        lengths = sorted({lng for lng in
                          set(range(bs, n + 1, bs)) | {n, n - 1}
                          if lng >= 1})
        for lng in lengths:
            nblk = -(-lng // bs)
            blocks = tuple(int(b) for b in table_row[:nblk])
            key = self._key(tokens, lng)
            with self._lock:
                if key in self._entries:
                    self._entries.move_to_end(key)
                    continue
                self._alloc.incref(blocks)
                self._entries[key] = (lng, blocks)
                self.insertions += 1
                while len(self._entries) > self.capacity:
                    self._evict_one_locked()

    def _evict_one_locked(self):
        key = next(iter(self._entries))
        _lng, blocks = self._entries.pop(key)
        self.evictions += 1
        self._alloc.decref(blocks)

    def evict_for(self, n_blocks):
        """Evict LRU entries until the allocator has ``n_blocks`` free
        or the cache is empty (entries whose blocks are still shared by
        live sessions free nothing — keep going).  Returns the number
        of blocks actually freed."""
        freed = 0
        while self._alloc.available() < n_blocks:
            with self._lock:
                if not self._entries:
                    break
                key = next(iter(self._entries))
                _lng, blocks = self._entries.pop(key)
                self.evictions += 1
            freed += len(self._alloc.decref(blocks))
        return freed

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def clear(self):
        """Drop every entry WITHOUT releasing references — only valid
        when the owning pool resets its allocator in the same breath
        (engine restart)."""
        with self._lock:
            self._entries = OrderedDict()


class KVBlockPool:
    """Per-engine facade: sizing, per-slot block tables, admission
    planning, boundary appends, release, and the ``serving.kv.*``
    telemetry families.

    The engine owns the device arrays and the compiled programs; this
    object owns which pool row means what.  All mutating calls run on
    the engine thread (single writer); reads for describe/healthz go
    through the allocator's lock or read monotonic counters.
    """

    def __init__(self, cfg, slots, *, block_size=None, num_blocks=None,
                 prefix_cache=None, model="lm", replica="0"):
        self.cfg = cfg
        self.slots = int(slots)
        bs = int(block_size) if block_size is not None \
            else _env_int("MXNET_KV_BLOCK_SIZE", 16)
        if bs < 1 or bs > cfg.max_len:
            raise MXNetError(
                "MXNET_KV_BLOCK_SIZE=%d must be within 1..max_len=%d"
                % (bs, cfg.max_len))
        self.block_size = bs
        #: table width: blocks that cover one max_len session
        self.max_blocks = -(-cfg.max_len // bs)
        nb = int(num_blocks) if num_blocks is not None \
            else _env_int("MXNET_KV_BLOCKS", 0)
        if nb <= 0:
            # dense-equivalent default (+ scratch): paged is never
            # worse than dense out of the box; undersize deliberately
            # to oversubscribe
            nb = self.slots * self.max_blocks + 1
        if nb < self.max_blocks + 1:
            raise MXNetError(
                "MXNET_KV_BLOCKS=%d cannot hold one max_len=%d session "
                "(needs >= %d blocks of %d tokens + scratch)"
                % (nb, cfg.max_len, self.max_blocks, bs))
        self.num_blocks = nb
        self.allocator = BlockAllocator(nb, bs)
        if prefix_cache is None:
            prefix_cache = (os.environ.get("MXNET_KV_PREFIX_CACHE", "1")
                            or "1").strip().lower() \
                not in ("0", "false", "off")
        self.cache = PrefixCache(self.allocator,
                                 enabled=bool(prefix_cache))
        self.tables = np.zeros((self.slots, self.max_blocks), np.int32)
        self._model = model
        self._replica = str(replica)
        # monotonic stats (engine-thread writer, racy-read safe)
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        self.cow_copies = 0
        labels = {"model": model, "replica": self._replica}
        _telemetry.inc("serving.kv.prefix_hits", 0, **labels)
        _telemetry.inc("serving.kv.cow_copies", 0, **labels)
        _telemetry.set_gauge("serving.kv.sessions_per_hbm_gb", 0.0,
                             **labels)
        self._gauges()

    # -- sizing ------------------------------------------------------------
    def hbm_bytes(self):
        """Device bytes the K+V pools occupy (float32)."""
        hd = self.cfg.embed // self.cfg.heads
        return (2 * self.cfg.layers * self.num_blocks * self.block_size
                * self.cfg.heads * hd * 4)

    def admissible(self, n_tokens):
        """Submit-time budget check: can a transcript of ``n_tokens``
        EVER be admitted — worst case (cold, no prefix sharing) it
        needs blocks for positions ``0..n_tokens`` against the whole
        allocatable pool.  Dynamic pressure is not checked here:
        queued sessions wait for blocks to free, they are not shed."""
        need = int(n_tokens) // self.block_size + 1
        return need <= self.num_blocks - 1

    # -- admission ---------------------------------------------------------
    def admit(self, slot, tokens):
        """Plan block storage for transcript ``tokens`` entering
        ``slot``: longest-prefix match against the cache shares full
        blocks by reference, a partial tail block becomes an in-graph
        copy-on-write, and fresh blocks cover the rest through position
        ``len(tokens)`` (the first sampled token's row).  Returns an
        :class:`AdmitPlan`; raises :class:`KVBlocksExhausted` — taking
        nothing — when even prefix-cache eviction cannot cover it."""
        tokens = np.asarray(tokens, np.int32)
        n = int(tokens.size)
        row = self.tables[slot]
        if row.any():
            raise MXNetError(
                "KV admit into slot %d which still holds blocks"
                % int(slot))
        bs = self.block_size
        matched, shared = self.cache.lookup(tokens)
        nfull, rem = divmod(matched, bs)
        last_blk = n // bs
        first_fresh = nfull + (1 if rem else 0)
        need = (1 if rem else 0) + max(last_blk - first_fresh + 1, 0)
        try:
            fresh = self._reserve(need)
        except KVBlocksExhausted:
            if shared:
                self.allocator.decref(shared)
            raise
        cow_src = cow_dst = 0
        if nfull:
            row[:nfull] = shared[:nfull]
        take = 0
        if rem:
            # the shared tail block is only partially prefix — copy it
            # on write: the prefill program duplicates the row before
            # the suffix scatters into the copy.  Our lookup reference
            # on the source is dropped now; the copy is read by the
            # very next dispatch in the engine's donated-state chain,
            # so the source row cannot be recycled underneath it.
            cow_src, cow_dst = int(shared[nfull]), int(fresh[0])
            row[nfull] = cow_dst
            take = 1
            self.allocator.decref([cow_src])
        if need - take:
            row[first_fresh:last_blk + 1] = fresh[take:]
        if matched:
            self.prefix_hits += 1
            self.prefix_tokens_reused += matched
            _telemetry.inc("serving.kv.prefix_hits", model=self._model,
                           replica=self._replica)
        if rem:
            self.cow_copies += 1
            _telemetry.inc("serving.kv.cow_copies", model=self._model,
                           replica=self._replica)
        self._gauges()
        return AdmitPlan(start=matched, cow_src=cow_src, cow_dst=cow_dst,
                         prefix_hit=bool(matched), reused_tokens=matched)

    def _reserve(self, need):
        if need <= 0:
            return []
        if self.allocator.available() < need:
            # cached prefixes never starve live admissions
            self.cache.evict_for(need)
        return self.allocator.alloc(need)

    def append(self, slot, pos):
        """Make sure the block covering position ``pos`` is allocated
        in ``slot``'s table (the decode loop calls this before every
        step for each live slot — a no-op except on block boundaries).
        Raises :class:`KVBlocksExhausted` when the pool is dry even
        after eviction; the engine sheds that session typed."""
        blk = int(pos) // self.block_size
        row = self.tables[slot]
        if row[blk]:
            return False
        (bid,) = self._reserve(1)
        row[blk] = bid
        self._gauges()
        return True

    def release(self, slot):
        """Drop the slot's references (retire/cancel/shed/migrate-out);
        blocks shared with the prefix cache or other slots survive."""
        row = self.tables[slot]
        held = [int(b) for b in row[row != 0]]
        row[:] = 0
        if held:
            self.allocator.decref(held)
        self._gauges()

    def offer(self, slot, prompt):
        """Index the slot's (just prefilled) prompt in the prefix
        cache so future sessions sharing it admit by reference."""
        self.cache.insert(np.asarray(prompt, np.int32),
                          self.tables[slot])

    def reset(self):
        """Forget all host state (engine restart/poisoned dispatch —
        the device pools were rebuilt from zeros)."""
        self.cache.clear()
        self.allocator.reset()
        self.tables[:] = 0
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        self.cow_copies = 0
        self._gauges()

    # -- observability -----------------------------------------------------
    def _gauges(self):
        labels = {"model": self._model, "replica": self._replica}
        _telemetry.set_gauge("serving.kv.blocks_used",
                             float(self.allocator.used()), **labels)
        _telemetry.set_gauge("serving.kv.blocks_free",
                             float(self.allocator.available()), **labels)

    def note_sessions(self, active):
        """Stamp ``serving.kv.sessions_per_hbm_gb`` — live sessions per
        GiB of KV storage, THE capacity headline the paged design
        exists to raise (the dense engine's is fixed at
        ``slots / dense_gb`` no matter how short its sessions are)."""
        gb = self.hbm_bytes() / float(1 << 30)
        _telemetry.set_gauge("serving.kv.sessions_per_hbm_gb",
                             float(active) / gb, model=self._model,
                             replica=self._replica)

    def describe(self):
        return {"layout": "paged",
                "block_size": self.block_size,
                "num_blocks": self.num_blocks,
                "max_blocks_per_session": self.max_blocks,
                "blocks_used": self.allocator.used(),
                "blocks_free": self.allocator.available(),
                "prefix_hits": self.prefix_hits,
                "prefix_tokens_reused": self.prefix_tokens_reused,
                "cow_copies": self.cow_copies,
                "prefix_entries": len(self.cache),
                "prefix_evictions": self.cache.evictions,
                "hbm_bytes": self.hbm_bytes()}
