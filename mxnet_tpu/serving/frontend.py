"""Serving frontends: in-process handle + stdlib HTTP/JSON endpoint.

:class:`ServingHandle` is the zero-copy in-process surface (what an
embedding application calls).  :class:`ServingHTTPServer` exposes the
same registry over ``http.server`` — no web framework, matching the
repo's no-new-deps rule — with three routes:

* ``POST /predict`` — ``{"model": name, "data": nested-list,
  "deadline_ms": optional}`` → ``{"model", "version", "shape",
  "output"}``; typed failures map to HTTP: :class:`Overloaded` → 429,
  :class:`DeadlineExceeded` → 504, :class:`UnknownModel` → 404.
* ``GET /healthz`` — liveness + the loaded model/version table.
* ``GET /metrics`` — the process-wide telemetry registry in Prometheus
  text exposition (PR 2's ``telemetry.prometheus_text``), scrapable.
"""

from __future__ import annotations

import json
import logging
import os
import signal as _signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import perfdebug as _perfdebug
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from ..base import MXNetError
from .batcher import (DeadlineExceeded, DynamicBatcher, InvalidRequest,
                      Overloaded)
from .registry import UnknownModel

__all__ = ["ServingHandle", "ServingHTTPServer"]

_log = logging.getLogger("mxnet_tpu.serving")


class ServingHandle:
    """In-process serving facade over a
    :class:`~mxnet_tpu.serving.registry.ModelRegistry`."""

    def __init__(self, registry):
        self.registry = registry

    def predict(self, model, data, deadline_ms=None,
                timeout=DynamicBatcher.DEFAULT_TIMEOUT):
        return self.registry.get(model).predict(
            data, deadline_ms=deadline_ms, timeout=timeout)

    def healthz(self):
        payload = {"status": "ok",
                   "models": {m.name: m.version
                              for m in self.registry.models()}}
        from .. import compile_cache as _compile_cache

        if _compile_cache.enabled():
            # operators watching a rolling version swap read cold==0
            # here as "the reload never recompiled" (docs/serving.md)
            cc = _compile_cache.stats()
            payload["compile_cache"] = {
                k: cc[k] for k in ("entries", "bytes", "hits", "misses",
                                   "evictions")}
        return payload

    def pending_rows(self):
        """Rows queued or in a device dispatch across every loaded
        model — the quiescence probe graceful drain polls."""
        total = 0
        for m in self.registry.models():
            batcher = getattr(m, "batcher", None)
            if batcher is not None:
                total += batcher.pending_rows()
        return total

    def metrics_text(self):
        return _telemetry.prometheus_text()


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-serving/1.0"
    protocol_version = "HTTP/1.1"
    #: request-body cap: one request must not be able to OOM the server
    max_body_bytes = 32 << 20

    def log_message(self, fmt, *args):
        # route through logging (operators filter), never bare stdout
        _log.debug("%s %s", self.address_string(), fmt % args)

    def _send(self, code, payload, content_type="application/json"):
        body = payload if isinstance(payload, bytes) \
            else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _count(self):
        # label cardinality stays bounded: scanner/bot paths must not
        # mint one permanent counter entry per distinct URL
        route = self.path if self.path in ("/predict", "/healthz",
                                           "/metrics") else "other"
        _telemetry.inc("serving.http.requests", route=route)

    def do_GET(self):
        handle = self.server.serving_handle
        self._count()
        if self.path == "/healthz":
            payload = handle.healthz()
            if getattr(self.server, "draining", False):
                # a draining replica must fail readiness so the load
                # balancer stops routing to it while in-flight work
                # finishes
                payload["status"] = "draining"
                return self._send(503, payload)
            self._send(200, payload)
        elif self.path == "/metrics":
            self._send(200, handle.metrics_text().encode(),
                       content_type="text/plain; version=0.0.4")
        else:
            self._send(404, {"error": "unknown route %r" % self.path})

    def _drain_body(self):
        """Consume an unread request body so the keep-alive connection
        stays in sync for the next request (oversized bodies close the
        connection instead of stalling on a slow sender)."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        if length > (1 << 20):
            self.close_connection = True
        elif length > 0:
            self.rfile.read(length)

    def do_POST(self):
        self._count()
        chunked = "chunked" in (self.headers.get("Transfer-Encoding")
                                or "").lower()
        if self.path != "/predict":
            # an undrained body would desync this keep-alive connection
            if chunked:
                self.close_connection = True
            else:
                self._drain_body()
            return self._send(404, {"error": "unknown route %r"
                                    % self.path})
        if chunked:
            # we only read Content-Length bodies
            self.close_connection = True
            return self._send(411, {"error": "chunked bodies are not "
                                    "supported; send Content-Length"})
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if not 0 <= length <= self.max_body_bytes:
            # oversized/negative declarations must neither buffer the
            # body in RAM nor pin the handler thread on a read
            self.close_connection = True
            return self._send(413, {"error": "Content-Length must be in "
                                    "0..%d" % self.max_body_bytes})
        try:
            req = json.loads(self.rfile.read(length) or b"{}")
            model = req["model"]
            data = np.asarray(req["data"], np.float32)
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            timeout = float(req.get("timeout_s", 60.0))
        except (ValueError, KeyError, TypeError) as e:
            # the body may be partially read at this point; don't let the
            # next pipelined request parse the remainder as a request line
            self.close_connection = True
            return self._send(400, {"error": "bad /predict request: %s"
                                    % e})
        # admission is lock-coupled with the draining flag: drain()
        # flips the flag under the same lock, so a request can never
        # slip between the check and the in-flight count — quiescence
        # (pending_rows()==0 AND admitted==0) is race-free
        srv = self.server
        with srv.admission_lock:
            draining = getattr(srv, "draining", False)
            if not draining:
                srv.admitted_requests += 1
        if draining:
            # stop admitting: the drain window is for finishing what is
            # already queued, not for new work
            _telemetry.inc("serving.shed.count", reason="draining")
            return self._send(503, {"error": "server is draining "
                                    "(preemption); retry elsewhere"})
        # chrome-trace span for the whole request handling: the HTTP
        # half of a latency spike sits on the same timeline as the
        # batcher's dispatch span (and compile/fit spans)
        prof = _profiler.running()
        span_us = _profiler.now_us() if prof else 0.0
        try:
            handle = srv.serving_handle
            try:
                # resolve ONCE: the version reported is the version that
                # served, and a concurrent unload/reload can't turn a
                # completed prediction into a 404
                served = handle.registry.get(model)
                out = served.predict(data, deadline_ms=deadline_ms,
                                     timeout=timeout)
                version = served.version
            except InvalidRequest as e:
                return self._send(400, {"error": str(e)})
            except Overloaded as e:
                return self._send(429, {"error": str(e)})
            except DeadlineExceeded as e:
                return self._send(504, {"error": str(e)})
            except UnknownModel as e:
                return self._send(404, {"error": str(e)})
            except Exception as e:
                # a dispatch error re-raised from the batch (numpy shape
                # mismatch, injected fault, ...) must still produce an
                # HTTP response on this keep-alive connection, never a
                # handler crash with the client left hanging
                return self._send(500, {"error": str(e)})
            out = np.asarray(out)
            self._send(200, {"model": model, "version": version,
                             "shape": list(out.shape),
                             "output": out.tolist()})
        finally:
            with srv.admission_lock:
                srv.admitted_requests -= 1
            if prof:
                _profiler.record("serving:http:%s" % model, "serving",
                                 span_us, _profiler.now_us())


class ServingHTTPServer:
    """Threaded HTTP server over a registry; ``port=0`` binds an
    ephemeral port (read ``.port`` after construction).

    ::

        server = ServingHTTPServer(registry, port=8080).start()
        ...
        server.stop()
    """

    def __init__(self, registry, host="127.0.0.1", port=8080):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.serving_handle = ServingHandle(registry)
        self._httpd.draining = False
        # admission accounting for graceful drain: flag + count mutate
        # under ONE lock, so drain() cannot observe quiescence while an
        # admitted request is still on its way to the batcher
        self._httpd.admission_lock = threading.Lock()
        self._httpd.admitted_requests = 0
        self._thread = None

    @property
    def host(self):
        return self._httpd.server_address[0]

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="serving-http",
                daemon=True)
            self._thread.start()
            _log.info("serving: HTTP endpoint up at %s", self.url)
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    @property
    def draining(self):
        return self._httpd.draining

    def drain(self, deadline=None):
        """Graceful preemption shutdown (docs/resilience.md): stop
        admitting (``/predict`` → 503, ``/healthz`` → 503 "draining"),
        wait for every model batcher to go quiescent — queued plus
        in-flight dispatches — under ``deadline`` seconds
        (``MXNET_PREEMPT_DRAIN_DEADLINE``, default 30), then stop the
        listener.  Returns True when the drain completed before the
        deadline, False when work was still in flight at cutoff."""
        if deadline is None:
            deadline = float(os.environ.get(
                "MXNET_PREEMPT_DRAIN_DEADLINE", "30") or 30)
        with self._httpd.admission_lock:
            self._httpd.draining = True
        _telemetry.event("preemption", component="serving")
        _perfdebug.flight_dump("serving_drain", deadline=deadline)
        _log.warning("serving: draining (deadline %.1fs)", deadline)
        handle = self._httpd.serving_handle
        cutoff = time.monotonic() + deadline

        def _busy():
            with self._httpd.admission_lock:
                admitted = self._httpd.admitted_requests
            return admitted + handle.pending_rows()

        clean = True
        while _busy() > 0:
            if time.monotonic() >= cutoff:
                clean = False
                _log.warning(
                    "serving: drain deadline hit with %d requests/rows "
                    "still in flight; stopping anyway", _busy())
                break
            time.sleep(0.01)
        self.stop()
        _log.info("serving: drained %s", "cleanly" if clean
                  else "with deadline overrun")
        return clean

    def run_forever(self, drain_deadline=None):
        """Serve until SIGTERM/SIGINT, then drain gracefully — the
        blocking entry point a container deployment calls.  Handlers are
        installed for the scope and restored on every exit path
        (``ci/check_signal_restore.py`` lints this shape)."""
        if threading.current_thread() is not threading.main_thread():
            raise MXNetError("run_forever installs signal handlers and "
                             "must run on the main thread")
        self.start()
        stop_ev = threading.Event()

        def _on_signal(signum, frame):
            _telemetry.event("preemption", component="serving",
                             signal=signum)
            stop_ev.set()

        prev_term = _signal.signal(_signal.SIGTERM, _on_signal)
        try:
            prev_int = _signal.signal(_signal.SIGINT, _on_signal)
            try:
                stop_ev.wait()
                return self.drain(deadline=drain_deadline)
            finally:
                _signal.signal(_signal.SIGINT, prev_int)
        finally:
            _signal.signal(_signal.SIGTERM, prev_term)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
