"""Serving frontends: in-process handle + stdlib HTTP/JSON endpoint.

:class:`ServingHandle` is the zero-copy in-process surface (what an
embedding application calls).  :class:`ServingHTTPServer` exposes the
same registry over ``http.server`` — no web framework, matching the
repo's no-new-deps rule — with six routes:

* ``POST /predict`` — ``{"model": name, "data": nested-list,
  "deadline_ms": optional}`` → ``{"model", "version", "shape",
  "output"}``; typed failures map to HTTP: :class:`Overloaded` → 429,
  :class:`DeadlineExceeded` → 504, :class:`UnknownModel` → 404.
* ``POST /generate`` — autoregressive decode through a
  :class:`~mxnet_tpu.serving.pool.ReplicaPool` /
  :class:`~mxnet_tpu.serving.decode.DecodeEngine` servable:
  ``{"model", "prompt": [token ids], "max_new_tokens", "temperature",
  "stream", "tenant", "priority", "deadline_ms"}``.  With ``"stream":
  true`` the response is ``Transfer-Encoding: chunked`` ndjson — one
  ``{"token": id}`` line per generated token as it lands, an
  ``{"event": "failover", ...}`` line wherever the pool migrated the
  session to another replica mid-generation (the token stream itself
  is seamless: no token is repeated or lost across the boundary), then
  a ``{"done": true, "tokens": [...], "ttft_ms": ..., "migrations":
  n}`` summary line; without it, one JSON document after the sequence
  finishes.  Optional ``"seed"`` pins the sampling stream (temperature
  replays are bit-identical for the same seed).
* ``GET /models`` — every loaded servable's card (name, version,
  buckets, replica states, warm-up status).
* ``GET /healthz`` — liveness + model/version table + per-model detail
  (plus a fleet-controller summary block when one is attached).
* ``GET /fleet`` — the fleet controller's card: per-model autoscale /
  quarantine state, device placements, and the recent decision ring
  (404 when no controller is attached to the registry).
* ``GET /metrics`` — the process-wide telemetry registry in Prometheus
  text exposition (PR 2's ``telemetry.prometheus_text``), scrapable.
"""

from __future__ import annotations

import json
import logging
import os
import queue as _queue
import signal as _signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import perfdebug as _perfdebug
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..base import MXNetError
from .batcher import (DeadlineExceeded, DynamicBatcher, InvalidRequest,
                      Overloaded)
from .registry import UnknownModel

__all__ = ["ServingHandle", "ServingHTTPServer"]

_log = logging.getLogger("mxnet_tpu.serving")


class ServingHandle:
    """In-process serving facade over a
    :class:`~mxnet_tpu.serving.registry.ModelRegistry`."""

    def __init__(self, registry):
        self.registry = registry

    def predict(self, model, data, deadline_ms=None,
                timeout=DynamicBatcher.DEFAULT_TIMEOUT):
        return self.registry.get(model).predict(
            data, deadline_ms=deadline_ms, timeout=timeout)

    @staticmethod
    def start_session(servable, prompt, tenant=None, priority=5, **kw):
        """Start one generation on an already-resolved servable; the
        ONE pool-vs-engine dispatch point the HTTP handler and
        :meth:`generate` both use.  A pool's session surface is
        ``generate()`` and takes tenant/priority; a bare engine's is
        ``submit()`` (its ``generate()`` is the blocking convenience)
        and tenant/priority are dropped — there is no pool admission
        layer to enforce them."""
        if hasattr(servable, "replicas"):
            return servable.generate(prompt, tenant=tenant,
                                     priority=priority, **kw)
        gen = getattr(servable, "submit", None) \
            if hasattr(servable, "slots") else None
        if gen is None:
            raise InvalidRequest(
                "model %r serves /predict, not /generate"
                % getattr(servable, "name", "?"))
        return gen(prompt, **kw)

    def generate(self, model, prompt, **kw):
        """Route one generation request to ``model``; returns its
        session (see :meth:`start_session` for the dispatch rules)."""
        return self.start_session(self.registry.get(model), prompt, **kw)

    @staticmethod
    def _describe(m):
        desc = getattr(m, "describe", None)
        if desc is not None:
            return desc()
        return {"name": m.name, "version": m.version}

    def models_payload(self):
        """``GET /models``: every loaded servable's card."""
        return {"models": [self._describe(m)
                           for m in self.registry.models()]}

    def fleet_payload(self):
        """``GET /fleet``: the attached fleet controller's card, or
        None when the registry runs uncontrolled."""
        controller = getattr(self.registry, "controller", None)
        if controller is None:
            return None
        return controller.describe()

    def healthz(self):
        payload = {"status": "ok",
                   "models": {m.name: m.version
                              for m in self.registry.models()},
                   "detail": {m.name: self._describe(m)
                              for m in self.registry.models()}}
        from .. import compile_cache as _compile_cache

        if _compile_cache.enabled():
            # operators watching a rolling version swap read cold==0
            # here as "the reload never recompiled" (docs/serving.md)
            cc = _compile_cache.stats()
            payload["compile_cache"] = {
                k: cc[k] for k in ("entries", "bytes", "hits", "misses",
                                   "evictions")}
        # per-model KV-storage occupancy (paged decode tiers): the
        # capacity number an operator reads before anything else —
        # blocks_free hitting 0 is the "admissions will shed typed"
        # early warning
        kv = {}
        for mname, card in payload["detail"].items():
            k = card.get("kv") if isinstance(card, dict) else None
            if k:
                kv[mname] = k
        if kv:
            payload["kv"] = kv
        fleet = self.fleet_payload()
        if fleet is not None:
            # the summary an operator triages from before opening
            # /fleet: is the loop alive, who is shedding/quarantined,
            # and the last few decisions
            payload["fleet"] = {
                "running": fleet["running"], "ticks": fleet["ticks"],
                "models": fleet["models"],
                "decisions": fleet["decisions"][-5:]}
        return payload

    def pending_rows(self):
        """Rows queued or in a device dispatch across every loaded
        servable — the quiescence probe graceful drain polls.  Decode
        pools count one row per queued-or-active sequence, so drain
        waits for in-flight generations too."""
        total = 0
        for m in self.registry.models():
            fn = getattr(m, "pending_rows", None)
            if fn is not None:
                total += fn()
                continue
            batcher = getattr(m, "batcher", None)
            if batcher is not None:
                total += batcher.pending_rows()
        return total

    def metrics_text(self):
        exp_dir = os.environ.get("MXNET_TELEMETRY_EXPORT_DIR")
        if exp_dir:
            # fleet mode: one scrape returns the MERGED view of every
            # process exporting into the shared directory (this one
            # included) — counters summed, gauges per-proc, histograms
            # bucket-merged
            return _telemetry.prometheus_text(
                _telemetry.aggregate(exp_dir, include_local=True))
        return _telemetry.prometheus_text()


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-serving/1.0"
    protocol_version = "HTTP/1.1"
    #: request-body cap: one request must not be able to OOM the server
    max_body_bytes = 32 << 20

    def log_message(self, fmt, *args):
        # route through logging (operators filter), never bare stdout
        _log.debug("%s %s", self.address_string(), fmt % args)

    def _send(self, code, payload, content_type="application/json"):
        body = payload if isinstance(payload, bytes) \
            else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _count(self):
        # label cardinality stays bounded: scanner/bot paths must not
        # mint one permanent counter entry per distinct URL
        route = self.path if self.path in ("/predict", "/generate",
                                           "/models", "/healthz",
                                           "/fleet", "/metrics") \
            else ("/trace" if self.path.startswith("/trace/")
                  else "other")
        _telemetry.inc("serving.http.requests", route=route)

    def do_GET(self):
        handle = self.server.serving_handle
        self._count()
        if self.path == "/healthz":
            payload = handle.healthz()
            if getattr(self.server, "draining", False):
                # a draining replica must fail readiness so the load
                # balancer stops routing to it while in-flight work
                # finishes
                payload["status"] = "draining"
                return self._send(503, payload)
            self._send(200, payload)
        elif self.path == "/models":
            self._send(200, handle.models_payload())
        elif self.path == "/fleet":
            fleet = handle.fleet_payload()
            if fleet is None:
                self._send(404, {"error": "no fleet controller is "
                                 "attached to this registry"})
            else:
                self._send(200, fleet)
        elif self.path == "/metrics":
            self._send(200, handle.metrics_text().encode(),
                       content_type="text/plain; version=0.0.4")
        elif self.path.startswith("/trace/"):
            tid = self.path[len("/trace/"):]
            tr = _tracing.tree(tid)
            if tr is None:
                self._send(404, {"error": "unknown trace %r (tracing "
                                 "off, id never minted, or evicted "
                                 "from the span ring)" % tid})
            else:
                self._send(200, tr)
        else:
            self._send(404, {"error": "unknown route %r" % self.path})

    def _admit_or_503(self, model):
        """Admission gate shared by /predict and /generate: lock-coupled
        with the draining flag — drain() flips the flag under the same
        lock, so a request can never slip between the check and the
        in-flight count and quiescence (pending_rows()==0 AND
        admitted==0) is race-free.  Returns True when admitted (the
        caller MUST decrement admitted_requests in a finally); when
        draining, sends the 503 and counts the shed — labeling with the
        model name only if it is actually loaded, so unauthenticated
        garbage cannot mint unbounded permanent telemetry label entries
        (the same bounded-cardinality rule as the route counter)."""
        srv = self.server
        with srv.admission_lock:
            draining = getattr(srv, "draining", False)
            if not draining:
                srv.admitted_requests += 1
                return True
        handle = srv.serving_handle
        known = handle.registry.get(model, default=None) is not None
        _telemetry.inc("serving.shed.count",
                       model=model if known else "other",
                       reason="drain")
        self._send(503, {"error": "server is draining (preemption); "
                         "retry elsewhere"})
        return False

    def _drain_body(self):
        """Consume an unread request body so the keep-alive connection
        stays in sync for the next request (oversized bodies close the
        connection instead of stalling on a slow sender)."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        if length > (1 << 20):
            self.close_connection = True
        elif length > 0:
            self.rfile.read(length)

    def do_POST(self):
        self._count()
        chunked = "chunked" in (self.headers.get("Transfer-Encoding")
                                or "").lower()
        if self.path not in ("/predict", "/generate"):
            # an undrained body would desync this keep-alive connection
            if chunked:
                self.close_connection = True
            else:
                self._drain_body()
            return self._send(404, {"error": "unknown route %r"
                                    % self.path})
        if chunked:
            # we only read Content-Length bodies
            self.close_connection = True
            return self._send(411, {"error": "chunked bodies are not "
                                    "supported; send Content-Length"})
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if not 0 <= length <= self.max_body_bytes:
            # oversized/negative declarations must neither buffer the
            # body in RAM nor pin the handler thread on a read
            self.close_connection = True
            return self._send(413, {"error": "Content-Length must be in "
                                    "0..%d" % self.max_body_bytes})
        try:
            req = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, TypeError) as e:
            # the body may be partially read at this point; don't let the
            # next pipelined request parse the remainder as a request line
            self.close_connection = True
            return self._send(400, {"error": "bad %s request: %s"
                                    % (self.path, e)})
        if self.path == "/generate":
            return self._do_generate(req)
        return self._do_predict(req)

    def _do_predict(self, req):
        try:
            model = req["model"]
            if not isinstance(model, str):
                raise TypeError("\"model\" must be a string")
            data = np.asarray(req["data"], np.float32)
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            timeout = float(req.get("timeout_s", 60.0))
        except (ValueError, KeyError, TypeError) as e:
            self.close_connection = True
            return self._send(400, {"error": "bad /predict request: %s"
                                    % e})
        if not self._admit_or_503(model):
            return
        srv = self.server
        # chrome-trace span for the whole request handling: the HTTP
        # half of a latency spike sits on the same timeline as the
        # batcher's dispatch span (and compile/fit spans)
        prof = _profiler.running()
        span_us = _profiler.now_us() if prof else 0.0
        # distributed-trace ROOT for the request: stacked on this
        # handler thread, so the batcher's submit-side span parents
        # under it automatically
        hsp = _tracing.start_span("serving.http.request",
                                  route="/predict", model=model)
        try:
            handle = srv.serving_handle
            try:
                # resolve ONCE: the version reported is the version that
                # served, and a concurrent unload/reload can't turn a
                # completed prediction into a 404
                served = handle.registry.get(model)
                if not hasattr(served, "predict"):
                    # a decode servable: the client's routing error
                    # (400), not a server fault — mirroring /generate's
                    # mapping for a predict-only model
                    raise InvalidRequest(
                        "model %r serves /generate, not /predict"
                        % model)
                out = served.predict(data, deadline_ms=deadline_ms,
                                     timeout=timeout)
                version = served.version
            except InvalidRequest as e:
                return self._send(400, {"error": str(e)})
            except Overloaded as e:
                return self._send(429, {"error": str(e)})
            except DeadlineExceeded as e:
                return self._send(504, {"error": str(e)})
            except UnknownModel as e:
                return self._send(404, {"error": str(e)})
            except Exception as e:
                # a dispatch error re-raised from the batch (numpy shape
                # mismatch, injected fault, ...) must still produce an
                # HTTP response on this keep-alive connection, never a
                # handler crash with the client left hanging
                return self._send(500, {"error": str(e)})
            out = np.asarray(out)
            self._send(200, {"model": model, "version": version,
                             "shape": list(out.shape),
                             "output": out.tolist()})
        finally:
            hsp.end("ok")
            with srv.admission_lock:
                srv.admitted_requests -= 1
            if prof:
                _profiler.record("serving:http:%s" % model, "serving",
                                 span_us, _profiler.now_us())

    # -- /generate ---------------------------------------------------------
    def _do_generate(self, req):
        try:
            model = req["model"]
            if not isinstance(model, str):
                raise TypeError("\"model\" must be a string")
            prompt = [int(t) for t in req["prompt"]]
            max_new = int(req.get("max_new_tokens", 16))
            temperature = float(req.get("temperature", 0.0))
            stream = bool(req.get("stream", False))
            tenant = req.get("tenant")
            priority = int(req.get("priority", 5))
            seed = req.get("seed")
            if seed is not None:
                seed = int(seed)
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            timeout = float(req.get("timeout_s", 60.0))
        except (ValueError, KeyError, TypeError) as e:
            self.close_connection = True
            return self._send(400, {"error": "bad /generate request: %s"
                                    % e})
        if not self._admit_or_503(model):
            return
        srv = self.server
        tok_q = _queue.Queue() if stream else None
        # request ROOT span: the session root opened inside
        # engine.submit() (same thread) parents under it, so GET
        # /trace/<id> shows HTTP -> generate -> admit/failover hops
        hsp = _tracing.start_span("serving.http.request",
                                  route="/generate", model=model)
        try:
            handle = srv.serving_handle
            kw = {"max_new_tokens": max_new, "temperature": temperature,
                  "deadline_ms": deadline_ms, "tenant": tenant,
                  "priority": priority, "seed": seed}
            if stream:
                # ONE ordered queue carries both tokens and failover
                # notifications: the {"event": "failover"} line lands
                # exactly at the migration boundary of the token stream
                kw["on_token"] = lambda t: tok_q.put(("token", t))
                kw["on_event"] = \
                    lambda kind, info: tok_q.put(("event", kind, info))
            try:
                # resolve ONCE (version-swap safety, as /predict) and
                # dispatch through the ONE routing point
                servable = handle.registry.get(model)
                sess = handle.start_session(servable, prompt, **kw)
            except InvalidRequest as e:
                return self._send(400, {"error": str(e)})
            except Overloaded as e:
                return self._send(429, {"error": str(e)})
            except UnknownModel as e:
                return self._send(404, {"error": str(e)})
            except Exception as e:
                # e.g. a closed pool hit mid version-swap: the straggler
                # gets a typed HTTP error, never a dropped connection
                return self._send(500, {"error": str(e)})
            version = servable.version
            if not stream:
                try:
                    tokens = sess.result(timeout)
                except DeadlineExceeded as e:
                    sess.cancel()
                    return self._send(504, {"error": str(e)})
                except Exception as e:
                    return self._send(500, {"error": str(e)})
                ttft = sess.ttft()
                return self._send(200, {
                    "model": model, "version": version,
                    "tokens": tokens, "n_tokens": len(tokens),
                    "trace_id": hsp.trace_id if hsp else None,
                    "ttft_ms": None if ttft is None
                    else round(ttft * 1e3, 3)})
            self._stream_session(model, version, sess, tok_q, timeout,
                                 trace_id=hsp.trace_id if hsp else None)
        finally:
            hsp.end("ok")
            with srv.admission_lock:
                srv.admitted_requests -= 1

    def _write_chunk(self, payload):
        line = (json.dumps(payload) + "\n").encode()
        self.wfile.write(b"%x\r\n%s\r\n" % (len(line), line))

    def _write_stream_item(self, item):
        """One queue entry -> one ndjson line: ``("token", id)`` or
        ``("event", kind, info)`` — a migration boundary becomes an
        explicit ``{"event": "failover", ...}`` line so a consumer can
        tell a mid-stream replica move from ordinary latency."""
        if item[0] == "event":
            _, kind, info = item
            self._write_chunk(dict({"event": kind}, **(info or {})))
        else:
            self._write_chunk({"token": int(item[1])})

    def _stream_session(self, model, version, sess, tok_q, timeout,
                        trace_id=None):
        """Chunked ndjson streaming: one ``{"token": id}`` line per
        generated token AS IT LANDS (the engine's ``on_token`` callback
        feeds the queue from its loop thread), interleaved with
        ``{"event": "failover"}`` lines at migration boundaries, then
        one summary line.  A vanished client cancels the session — the
        SAME session object rides every migration, so the cancel
        reaches whichever replica currently holds it (no orphaned slot
        on the new replica)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        cutoff = time.monotonic() + timeout
        try:
            while True:
                try:
                    self._write_stream_item(tok_q.get(timeout=0.05))
                    continue
                except _queue.Empty:
                    pass
                if sess.done():
                    # drain stragglers enqueued between Empty and done()
                    while True:
                        try:
                            self._write_stream_item(tok_q.get_nowait())
                        except _queue.Empty:
                            break
                    break
                if time.monotonic() > cutoff:
                    sess.cancel()
                    self._write_chunk({"error": "stream timeout after "
                                       "%.1fs" % timeout})
                    break
            try:
                tokens = sess.result(timeout=5.0)
                ttft = sess.ttft()
                self._write_chunk({"done": True, "tokens": tokens,
                                   "n_tokens": len(tokens),
                                   "model": model, "version": version,
                                   "migrations": getattr(sess,
                                                         "migrations", 0),
                                   "trace_id": trace_id,
                                   "ttft_ms": None if ttft is None
                                   else round(ttft * 1e3, 3)})
            except Exception as e:
                self._write_chunk({"error": str(e)})
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionError, OSError):
            # client went away mid-stream: free the slot, drop the
            # connection (it is desynced anyway)
            sess.cancel()
            self.close_connection = True


class ServingHTTPServer:
    """Threaded HTTP server over a registry; ``port=0`` binds an
    ephemeral port (read ``.port`` after construction).

    ::

        server = ServingHTTPServer(registry, port=8080).start()
        ...
        server.stop()
    """

    def __init__(self, registry, host="127.0.0.1", port=8080):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.serving_handle = ServingHandle(registry)
        self._httpd.draining = False
        # admission accounting for graceful drain: flag + count mutate
        # under ONE lock, so drain() cannot observe quiescence while an
        # admitted request is still on its way to the batcher
        self._httpd.admission_lock = threading.Lock()
        self._httpd.admitted_requests = 0
        self._thread = None

    @property
    def host(self):
        return self._httpd.server_address[0]

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="serving-http",
                daemon=True)
            self._thread.start()
            _log.info("serving: HTTP endpoint up at %s", self.url)
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    @property
    def draining(self):
        return self._httpd.draining

    def drain(self, deadline=None):
        """Graceful preemption shutdown (docs/resilience.md): stop
        admitting (``/predict`` → 503, ``/healthz`` → 503 "draining"),
        wait for every model batcher to go quiescent — queued plus
        in-flight dispatches — under ``deadline`` seconds
        (``MXNET_PREEMPT_DRAIN_DEADLINE``, default 30), then stop the
        listener.  Returns True when the drain completed before the
        deadline, False when work was still in flight at cutoff."""
        if deadline is None:
            deadline = float(os.environ.get(
                "MXNET_PREEMPT_DRAIN_DEADLINE", "30") or 30)
        with self._httpd.admission_lock:
            self._httpd.draining = True
        _telemetry.event("preemption", component="serving")
        _perfdebug.flight_dump("serving_drain", deadline=deadline)
        _log.warning("serving: draining (deadline %.1fs)", deadline)
        handle = self._httpd.serving_handle
        cutoff = time.monotonic() + deadline

        def _busy():
            with self._httpd.admission_lock:
                admitted = self._httpd.admitted_requests
            return admitted + handle.pending_rows()

        clean = True
        while _busy() > 0:
            if time.monotonic() >= cutoff:
                clean = False
                _log.warning(
                    "serving: drain deadline hit with %d requests/rows "
                    "still in flight; stopping anyway", _busy())
                break
            time.sleep(0.01)
        self.stop()
        _log.info("serving: drained %s", "cleanly" if clean
                  else "with deadline overrun")
        return clean

    def run_forever(self, drain_deadline=None):
        """Serve until SIGTERM/SIGINT, then drain gracefully — the
        blocking entry point a container deployment calls.  Handlers are
        installed for the scope and restored on every exit path
        (the graftlint signal-restore pass lints this shape)."""
        if threading.current_thread() is not threading.main_thread():
            raise MXNetError("run_forever installs signal handlers and "
                             "must run on the main thread")
        self.start()
        stop_ev = threading.Event()

        def _on_signal(signum, frame):
            _telemetry.event("preemption", component="serving",
                             signal=signum)
            stop_ev.set()

        prev_term = _signal.signal(_signal.SIGTERM, _on_signal)
        try:
            prev_int = _signal.signal(_signal.SIGINT, _on_signal)
            try:
                stop_ev.wait()
                return self.drain(deadline=drain_deadline)
            finally:
                _signal.signal(_signal.SIGINT, prev_int)
        finally:
            _signal.signal(_signal.SIGTERM, prev_term)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
