"""Continuous-batching decode engine — autoregressive serving on slots.

The PR 3 batcher coalesces *fixed-shape* requests; an autoregressive LM
breaks that model: every sequence wants a different number of steps, and
naive batching waits for the slowest sequence while the rest of the
batch pads along dead.  The TPU-native answer is the same move the
sync-free fit loop made for training (docs/how_to/perf.md): make the
decode loop ONE fixed-shape jitted step that never recompiles and never
syncs beyond a single packed host read per token.

:class:`DecodeEngine` owns a device-resident KV cache of fixed shape
``(S slots, max_len)`` per layer and exactly TWO compiled programs:

* **prefill** (one per declared prompt-length bucket): run a
  bucket-padded prompt, scatter its K/V rows into a free slot, sample
  the first token and arm the slot — all in-graph;
* **decode step** (one per ``(S, max_len)``): advance ALL slots one
  token — scatter the incoming token's K/V, attend over each slot's
  ``<= length`` horizon, sample (greedy or temperature, keys split
  in-graph from :mod:`mxnet_tpu.random` seed material), retire
  EOS/length-done slots — returning the packed ``(token, done,
  active)`` buffer whose single host read is the loop's only sync.

Sequences are admitted into free slots BETWEEN steps (continuous
batching: a late request joins the running batch instead of waiting for
it), retired on EOS/length without recompiling, and stream their tokens
out through per-session callbacks.  Inactive slots ride along at fixed
shape; their scatter rows are unreachable under the attention mask
until a real write replaces them.

The engine is single-device; multi-replica throughput is
:class:`~mxnet_tpu.serving.pool.ReplicaPool`'s job.  The hot loop is
covered by the graftlint host-sync pass (``ci/graftlint``): the packed
per-step read is the one sanctioned transfer.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

import numpy as np

from .. import compile_cache as _compile_cache
from .. import faults as _faults
from .. import perfdebug as _perfdebug
from .. import random as _random
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..models import transformer_lm as _tlm
from .batcher import (LATENCY_BUCKETS, DeadlineExceeded, Future,
                      InvalidRequest, Overloaded)

__all__ = ["GenerateSession", "DecodeEngine", "TTFT_BUCKETS"]

_log = logging.getLogger("mxnet_tpu.serving")

#: time-to-first-token histogram bounds (seconds) — first tokens pay a
#: queue wait + one prefill, so the ladder reaches further than the
#: per-token one
TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0)


# shared int-env parser — ONE definition lives in compile_cache.py
# (pool.py imports it from there too)
from ..compile_cache import _env_int  # noqa: E402


class GenerateSession:
    """One streaming generation request: queued -> active(slot) ->
    done/shed.  ``result()`` blocks for the full token list (prompt NOT
    included; EOS, when hit, is the last token); ``on_token`` streams
    each token from the engine thread (must be cheap and non-blocking —
    HTTP streaming hands it a queue put)."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "deadline",
                 "on_token", "tokens", "future", "t_submit", "t_first",
                 "t_done", "slot", "admit_step", "done_step", "_finished",
                 "_on_done")

    def __init__(self, prompt, max_new_tokens, temperature, deadline_ms,
                 on_token, on_done=None):
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        self.on_token = on_token
        self.tokens = []
        self.future = Future()
        self.t_submit = time.monotonic()
        self.t_first = None
        self.t_done = None
        self.slot = None
        self.admit_step = None
        self.done_step = None
        self._finished = False
        self._on_done = on_done

    def cancel(self):
        """Abandon the request: queued sessions are dropped at the next
        admission scan, an active session is retired (its slot freed) at
        the next step boundary.  Returns False when the session already
        finished.  ONE cancellation flag — the embedded Future's (the
        same machinery the batcher honors), so ``sess.future.cancel()``
        and ``sess.cancel()`` cannot diverge."""
        return self.future.cancel()

    def cancelled(self):
        return self.future.cancelled()

    def done(self):
        return self.future.done()

    def result(self, timeout=60.0):
        """Block for the full generated token list (re-raising the shed
        or dispatch error when the session failed)."""
        return self.future.result(timeout)

    def ttft(self):
        """Time-to-first-token in seconds (None before the first
        token)."""
        return None if self.t_first is None \
            else self.t_first - self.t_submit


class DecodeEngine:
    """Slot-based continuous batching over one
    :mod:`~mxnet_tpu.models.transformer_lm` replica.

    Parameters
    ----------
    cfg : transformer_lm.LMConfig
    params : pytree
        Host or device params; committed to ``device``.
    slots : int
        Concurrent sequences S (``MXNET_DECODE_SLOTS`` default, 8).
        The decode step compiles once per ``(S, max_len)``.
    prefill_buckets : tuple of int
        Declared prompt-length buckets; a prompt pads to the smallest
        bucket that fits (``DynamicBatcher`` bucket idiom — the jit
        cache sees ``len(prefill_buckets)`` prefill shapes, ever).
    max_queue : int
        Admission bound on QUEUED sessions; past it ``submit`` raises
        :class:`Overloaded`.
    device : jax.Device, optional
        Replica placement; defaults to the process default device.
    replica : str
        Telemetry label (``replica=<id>``) — the pool names replicas.
    on_step_error / on_step_ok : callable, optional
        Replica-health hooks (the pool's quarantine counter); called
        outside the engine lock.
    """

    def __init__(self, cfg, params, *, slots=None, prefill_buckets=(8, 32),
                 max_queue=64, device=None, name="lm", replica="0",
                 autostart=True, on_step_error=None, on_step_ok=None):
        import jax

        self.cfg = cfg
        self.name = name
        self.replica = str(replica)
        self.slots = int(slots) if slots is not None \
            else _env_int("MXNET_DECODE_SLOTS", 8)
        if self.slots < 1:
            raise MXNetError("DecodeEngine needs >= 1 slot")
        buckets = tuple(sorted({int(b) for b in prefill_buckets}))
        if not buckets or buckets[0] < 1 or buckets[-1] > cfg.max_len:
            raise MXNetError(
                "prefill buckets %r must be within 1..max_len=%d"
                % (buckets, cfg.max_len))
        self.prefill_buckets = buckets
        self.max_queue = int(max_queue)
        self._device = device if device is not None else jax.devices()[0]
        self._params = jax.device_put(params, self._device)
        self._on_step_error = on_step_error
        self._on_step_ok = on_step_ok

        self._cond = threading.Condition(threading.Lock())
        self._queue = deque()
        self._slot_sessions = [None] * self.slots
        self._running = False
        self._draining = False
        self._closed = False
        self._thread = None
        #: total decode steps (tests pin continuous admission on it)
        self.steps = 0
        #: total generated tokens
        self.tokens_out = 0
        self._rate_t0 = time.monotonic()
        self._rate_tokens = 0

        self._step_fn = None       # built in _build()
        self._prefill_fns = {}
        self._boot_state = self._build()
        labels = {"model": name, "replica": self.replica}
        _telemetry.inc("serving.decode.sessions.count", 0, **labels)
        _telemetry.inc("serving.decode.tokens.count", 0, **labels)
        _telemetry.inc("serving.decode.steps.count", 0, **labels)
        _telemetry.set_gauge("serving.decode.slot_occupancy", 0.0, **labels)
        _telemetry.set_gauge("serving.decode.tokens_per_sec", 0.0, **labels)
        for reason in ("deadline", "overload", "abandoned", "drain"):
            _telemetry.inc("serving.shed.count", 0, model=name,
                           reason=reason)
        if autostart:
            self.start()

    # -- compiled programs -------------------------------------------------
    def _build(self):
        """Build the two jitted programs and the initial device state;
        warm-compile every shape so no live request ever eats a trace
        (persistent-cache loads on a warm reload, PR 7)."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        s, m = self.slots, cfg.max_len
        eos = np.int32(cfg.eos_id)

        def sample(key, logits, temps):
            # greedy when temperature == 0, else temperature sampling;
            # per-slot keys split in-graph — the loop never touches the
            # host RNG
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            skeys = jax.random.split(key, logits.shape[0])
            drawn = jax.vmap(
                lambda kk, lg, tt: jax.random.categorical(
                    kk, lg / jnp.maximum(tt, 1e-6)))(
                        skeys, logits, temps).astype(jnp.int32)
            return jnp.where(temps > 0.0, drawn, greedy)

        def step(params, state, keep):
            cache_k, cache_v, last_tok, lengths, limits, active, temps, \
                key = state
            active = active & keep
            logits, cache_k, cache_v = _tlm.decode_step_math(
                cfg, params, cache_k, cache_v, last_tok, lengths)
            key, sub = jax.random.split(key)
            tok = sample(sub, logits, temps)
            new_len = lengths + active.astype(jnp.int32)
            done = active & ((tok == eos) | (new_len >= limits))
            new_active = active & ~done
            new_last = jnp.where(active, tok, last_tok)
            packed = jnp.stack([jnp.where(active, tok, -1),
                                done.astype(jnp.int32),
                                new_active.astype(jnp.int32)])
            return (cache_k, cache_v, new_last, new_len, limits,
                    new_active, temps, key), packed

        def prefill(params, state, tokens, length, slot, limit, temp,
                    activate):
            cache_k, cache_v, last_tok, lengths, limits, active, temps, \
                key = state
            last_logits, ks, vs = _tlm.prefill_kv(cfg, params, tokens,
                                                  length)
            cache_k = tuple(
                jax.lax.dynamic_update_slice(ck, k[None], (slot, 0, 0, 0))
                for ck, k in zip(cache_k, ks))
            cache_v = tuple(
                jax.lax.dynamic_update_slice(cv, v[None], (slot, 0, 0, 0))
                for cv, v in zip(cache_v, vs))
            key, sub = jax.random.split(key)
            tok = sample(sub, last_logits[None],
                         jnp.full((1,), temp))[0]
            first_done = (tok == eos) | (limit <= length)
            arm = activate & ~first_done
            last_tok = last_tok.at[slot].set(tok)
            lengths = lengths.at[slot].set(length)
            limits = limits.at[slot].set(limit)
            temps = temps.at[slot].set(temp)
            active = active.at[slot].set(arm)
            out = jnp.stack([tok, first_done.astype(jnp.int32)])
            return (cache_k, cache_v, last_tok, lengths, limits, active,
                    temps, key), out

        self._step_fn = self._instrument(
            jax.jit(step, donate_argnums=(1,)), "decode_step",
            ("decode_step", s, m))
        pf_jit = jax.jit(prefill, donate_argnums=(1,))
        self._prefill_fns = {
            b: self._instrument(pf_jit, "decode_prefill",
                                ("decode_prefill", b, s, m))
            for b in self.prefill_buckets}

        state = self._fresh_state()
        with _compile_cache.recording_scope() as rec:
            cc0 = _compile_cache.stats() if _compile_cache.enabled() \
                else None
            state = self._warm(state)
            cc1 = _compile_cache.stats() if cc0 is not None else None
        self.warmup_entries = rec.entries
        if cc0 is not None:
            # a separate family from the batcher's serving.warmup.* —
            # this one carries a replica label, and a telemetry family
            # must never mix label sets
            _telemetry.set_gauge(
                "serving.decode.warmup.cold_compiles",
                cc1["misses"] - cc0["misses"], model=self.name,
                replica=self.replica)
            _telemetry.set_gauge(
                "serving.decode.warmup.cache_loads",
                cc1["hits"] - cc0["hits"], model=self.name,
                replica=self.replica)
        _telemetry.event("serving.decode.warm", model=self.name,
                         replica=self.replica, slots=s,
                         buckets=len(self.prefill_buckets))
        return state

    def _instrument(self, fn, kind, build_kind):
        """First-call hook: count the compile (``xla.compile.count``,
        the recompile-detector's family) and record the build into the
        PR 7 warm-up manifest registry."""
        def hook(f, args, kwargs, dt):
            _telemetry.inc("xla.compile.count", kind=kind)
            _telemetry.inc("xla.compile.seconds", dt, kind=kind)
            if _compile_cache.recording():
                _compile_cache.note_build(
                    "serving:%s" % self.name, build_kind, f.lower, args,
                    kwargs, dt)
        return _perfdebug.first_call_hook(fn, hook)

    def _fresh_state(self):
        """Zeroed device-resident slot state, committed to the replica
        device."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        s, m = self.slots, cfg.max_len
        hd = cfg.embed // cfg.heads
        zeros_kv = tuple(jnp.zeros((s, m, cfg.heads, hd), jnp.float32)
                         for _ in range(cfg.layers))
        state = (zeros_kv,
                 tuple(jnp.zeros((s, m, cfg.heads, hd), jnp.float32)
                       for _ in range(cfg.layers)),
                 jnp.zeros((s,), jnp.int32),        # last_tok
                 jnp.zeros((s,), jnp.int32),        # lengths
                 jnp.zeros((s,), jnp.int32),        # limits
                 jnp.zeros((s,), bool),             # active
                 jnp.zeros((s,), jnp.float32),      # temps
                 jnp.asarray(np.array(_random.next_key()), jnp.uint32))
        return jax.device_put(state, self._device)

    def _warm(self, state):
        """Compile the decode step and every prefill bucket against the
        real state buffers — ``activate=False`` leaves the slots
        disarmed, so warm-up never corrupts serving state."""
        for b in self.prefill_buckets:
            state, _out = self._prefill_fns[b](
                self._params, state, np.zeros((b,), np.int32),
                np.int32(1), np.int32(0), np.int32(0), np.float32(0.0),
                np.bool_(False))
        state, _packed = self._step_fn(self._params, state,
                                       np.ones((self.slots,), bool))
        return state

    def set_health_hooks(self, on_error=None, on_ok=None):
        """Install the pool's replica-health hooks.  Call before
        :meth:`start` — plain attribute flips, deliberately outside the
        engine lock (the hooks take the POOL's lock; holding both here
        would order the locks both ways)."""
        self._on_step_error = on_error
        self._on_step_ok = on_ok

    def rewarm(self):
        """Recompile/reload every program (the pool's quarantine
        re-warm): with the persistent compile cache armed this is pure
        cache loads — zero cold compiles on a healthy host.  Refuses a
        running or CLOSED engine — a background re-warm racing a
        pointer-flip version swap must not resurrect the retired
        replica (the pool's except path leaves it quarantined)."""
        with self._cond:
            if self._running:
                raise MXNetError("rewarm() needs a stopped engine")
            if self._closed:
                raise MXNetError("decode engine %r is closed"
                                 % self.name)
        state = self._build()
        with self._cond:
            self._boot_state = state
            self._draining = False

    # -- client side -------------------------------------------------------
    def submit(self, prompt, *, max_new_tokens=16, temperature=0.0,
               deadline_ms=None, on_token=None, on_done=None):
        """Queue a generation request; returns its
        :class:`GenerateSession`.  Raises :class:`Overloaded` past the
        queue bound and :class:`InvalidRequest` for malformed prompts
        (the client's error, surfaced at submit)."""
        prompt = np.array(prompt, np.int32).ravel()
        if prompt.size < 1:
            raise InvalidRequest("empty prompt")
        if prompt.size > self.prefill_buckets[-1]:
            raise InvalidRequest(
                "prompt of %d tokens exceeds the largest prefill bucket "
                "%d" % (prompt.size, self.prefill_buckets[-1]))
        if prompt.size >= self.cfg.max_len:
            raise InvalidRequest(
                "prompt of %d tokens leaves no room under max_len=%d"
                % (prompt.size, self.cfg.max_len))
        if prompt.min() < 0 or prompt.max() >= self.cfg.vocab:
            raise InvalidRequest(
                "prompt token ids must be in 0..vocab-1=%d"
                % (self.cfg.vocab - 1))
        if int(max_new_tokens) < 1:
            raise InvalidRequest("max_new_tokens must be >= 1")
        if float(temperature) < 0:
            raise InvalidRequest("temperature must be >= 0")
        sess = GenerateSession(prompt, max_new_tokens, temperature,
                               deadline_ms, on_token, on_done)
        with self._cond:
            if self._closed:
                raise MXNetError("decode engine %r is closed" % self.name)
            if self._draining:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="drain")
                raise Overloaded("decode engine %r is draining"
                                 % self.name)
            if len(self._queue) >= self.max_queue:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="overload")
                raise Overloaded(
                    "decode engine %r overloaded: %d sessions queued"
                    % (self.name, len(self._queue)))
            # counted AFTER admission: sessions.count is accepted
            # sessions (completion/shed ratios read against it);
            # rejected submits show only in serving.shed.count
            _telemetry.inc("serving.decode.sessions.count",
                           model=self.name, replica=self.replica)
            self._queue.append(sess)
            self._cond.notify()
        return sess

    def generate(self, prompt, timeout=60.0, **kw):
        """Blocking convenience: ``submit`` + ``result``."""
        sess = self.submit(prompt, **kw)
        try:
            return sess.result(timeout)
        except DeadlineExceeded:
            sess.cancel()
            raise

    # -- introspection -----------------------------------------------------
    def pending_rows(self):
        """Queued plus active sessions — the graceful-drain quiescence
        probe (one row == one sequence)."""
        with self._cond:
            return len(self._queue) + \
                sum(1 for x in self._slot_sessions if x is not None)

    def outstanding(self):
        """Same number the pool's least-outstanding routing reads."""
        return self.pending_rows()

    def describe(self):
        with self._cond:
            active = sum(1 for x in self._slot_sessions if x is not None)
            queued = len(self._queue)
            steps = self.steps
            tokens = self.tokens_out
        return {"name": self.name, "kind": "generate",
                "version": getattr(self, "version", None),
                "replica": self.replica, "device": str(self._device),
                "slots": self.slots, "active": active, "queued": queued,
                "steps": steps, "tokens": tokens,
                "prefill_buckets": list(self.prefill_buckets),
                "max_len": self.cfg.max_len}

    # -- worker ------------------------------------------------------------
    def start(self):
        with self._cond:
            if self._closed:
                # a closed engine stays closed: restarting its worker
                # (e.g. a stale re-warm thread) would leak a spinning
                # daemon on a servable nobody routes to
                raise MXNetError("decode engine %r is closed"
                                 % self.name)
            if self._thread is not None:
                return self
            if self._boot_state is None:
                # restart after a plain stop(): the compiled programs
                # survive, only the slot state was consumed — rebuild
                # it from zeros (device_put, no recompile)
                self._boot_state = self._fresh_state()
            self._draining = False
            self._running = True
            self._thread = threading.Thread(
                target=self._serve_loop,
                name="decode-%s-%s" % (self.name, self.replica),
                daemon=True)
            self._thread.start()
        return self

    def stop(self, drain=True, deadline=None):
        """Stop the engine.  ``drain=True`` keeps stepping until every
        ACTIVE sequence finishes (new admissions stop; queued sessions
        are shed immediately with a typed error) under ``deadline``
        seconds (``MXNET_PREEMPT_DRAIN_DEADLINE``, default 30); past
        the deadline — or with ``drain=False`` — unfinished sessions
        are shed, never silently dropped.  Returns True when the drain
        completed cleanly."""
        if deadline is None:
            deadline = float(os.environ.get(
                "MXNET_PREEMPT_DRAIN_DEADLINE", "30") or 30)
        shed = []
        with self._cond:
            self._draining = True
            if not drain:
                self._running = False
            while self._queue:
                shed.append(self._queue.popleft())
            self._cond.notify_all()
        err = MXNetError("decode engine %r stopped before this session "
                         "was served" % self.name)
        clean = not shed
        for sess in shed:
            _telemetry.inc("serving.shed.count", model=self.name,
                           reason="drain")
            self._finish(sess, error=err)
        with self._cond:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=deadline if drain else 5.0)
            if t.is_alive():
                clean = False
                with self._cond:
                    self._running = False
                    self._cond.notify_all()
                t.join(timeout=10.0)
        # anything still holding a slot is shed with the typed error
        leftovers = []
        with self._cond:
            for i, sess in enumerate(self._slot_sessions):
                if sess is not None:
                    leftovers.append(sess)
                    self._slot_sessions[i] = None
        for sess in leftovers:
            clean = False
            _telemetry.inc("serving.shed.count", model=self.name,
                           reason="drain")
            self._finish(sess, error=err)
        self._occupancy_gauge()
        return clean

    def close(self, drain=True):
        """Permanent :meth:`stop`: further submits fail fast."""
        with self._cond:
            self._closed = True
        return self.stop(drain=drain)

    def _serve_loop(self):
        with self._cond:
            state = self._boot_state
            self._boot_state = None
        while True:
            admits = []
            shed = []  # (session, reason) — finished OUTSIDE the lock:
            # _finish runs the pool's on_done hook, which takes the POOL
            # lock, and pool.describe() takes pool-then-engine — holding
            # the engine lock here would order the locks both ways
            with self._cond:
                if not self._running:
                    return
                free = [i for i, x in enumerate(self._slot_sessions)
                        if x is None]
                # walk the WHOLE queue every iteration: abandoned or
                # expired entries must release the max_queue admission
                # bound (and the pool's outstanding accounting) even
                # while every slot is busy — the batcher's abandoned-
                # entry fix, applied here too.  FIFO order preserved.
                now = time.monotonic()
                keep = deque()
                while self._queue:
                    sess = self._queue.popleft()
                    if sess.cancelled():
                        shed.append((sess, "abandoned"))
                    elif sess.deadline is not None \
                            and now > sess.deadline:
                        shed.append((sess, "deadline"))
                    elif free:
                        sess.slot = free.pop(0)
                        self._slot_sessions[sess.slot] = sess
                        admits.append(sess)
                    else:
                        keep.append(sess)
                self._queue = keep
                have_active = any(x is not None
                                  for x in self._slot_sessions)
                if not admits and not shed and not have_active:
                    if self._draining:
                        self._running = False
                        return
                    self._cond.wait(0.02)
                    continue
            for sess, reason in shed:
                # every exit path resolves the future and fires on_done
                # — a dropped session would leak the pool's outstanding
                # accounting forever
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason=reason)
                err = DeadlineExceeded("deadline expired while queued "
                                       "for a decode slot") \
                    if reason == "deadline" else \
                    MXNetError("session abandoned by the client while "
                               "queued")
                self._finish(sess, error=err)
            for sess in admits:
                state, aborted = self._admit(sess, state)
                if aborted:
                    # _fail_all already resolved EVERY reserved slot —
                    # including admits not yet prefilled; touching them
                    # again would double-fire the pool's on_done hook
                    break
            with self._cond:
                have_active = any(x is not None
                                  for x in self._slot_sessions)
            if have_active:
                state = self._step(state)

    def _admit(self, sess, state):
        """Prefill ``sess`` into its (already reserved) slot: one
        bucket-shaped dispatch + one tiny admission-time host read for
        the first token (TTFT); the hot loop's own budget is untouched.
        Returns ``(state, aborted)`` — aborted=True means the dispatch
        poisoned the donated state and :meth:`_fail_all` already
        resolved every held session."""
        cfg = self.cfg
        p = int(sess.prompt.size)
        bucket = next(b for b in self.prefill_buckets if p <= b)
        tokens = np.zeros((bucket,), np.int32)
        tokens[:p] = sess.prompt
        limit = np.int32(min(p + sess.max_new_tokens - 1, cfg.max_len))
        try:
            state, out = self._prefill_fns[bucket](
                self._params, state, tokens, np.int32(p),
                np.int32(sess.slot), limit,
                np.float32(sess.temperature), np.bool_(True))
            out = np.asarray(out)  # lint: ok[host-sync] admission-time first-token read (TTFT), not the per-step hot loop
        except Exception as e:
            # a poisoned prefill poisons the whole donated state: fail
            # every session this engine holds and restart from zeros
            # (the queue is untouched)
            return self._fail_all(e, state), True
        sess.t_first = time.monotonic()
        tok = int(out[0])
        sess.tokens.append(tok)
        self._emit(sess, tok)
        _telemetry.observe("serving.decode.ttft_seconds",
                           sess.t_first - sess.t_submit,
                           buckets=TTFT_BUCKETS, model=self.name)
        _telemetry.inc("serving.decode.tokens.count", model=self.name,
                       replica=self.replica)
        with self._cond:
            sess.admit_step = self.steps
            self.tokens_out += 1
            self._rate_tokens += 1
        if out[1]:  # EOS or max_new_tokens == 1: done at prefill
            self._retire(sess)
        self._occupancy_gauge()
        return state, False

    def _step(self, state):
        """ONE fixed-shape decode dispatch for all slots + the single
        packed host read; host bookkeeping fans tokens out to sessions."""
        keep = np.ones((self.slots,), bool)
        with self._cond:
            sessions = list(self._slot_sessions)
        now = time.monotonic()
        for i, sess in enumerate(sessions):
            if sess is None:
                continue
            if sess.cancelled():
                keep[i] = False
            elif sess.deadline is not None and now > sess.deadline:
                keep[i] = False
        t0 = time.perf_counter()
        try:
            if _faults.should_fire("serving.decode"):
                raise _faults.FaultInjected(
                    "fault 'serving.decode': decode step of model %r "
                    "killed" % self.name)
            state, packed = self._step_fn(self._params, state, keep)
            packed = np.asarray(packed)  # lint: ok[host-sync] THE one sanctioned host read per decode step (packed token/done/active buffer)
        except Exception as e:
            return self._fail_all(e, state)
        dt = time.perf_counter() - t0
        emitted = 0
        for i, sess in enumerate(sessions):
            if sess is None:
                continue
            if not keep[i]:
                reason = "abandoned" if sess.cancelled() else "deadline"
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason=reason)
                err = DeadlineExceeded("session deadline expired "
                                       "mid-generation") \
                    if reason == "deadline" else \
                    MXNetError("session abandoned by the client")
                self._retire(sess, error=err)
                continue
            tok = int(packed[0, i])
            if tok >= 0:
                emitted += 1
                sess.tokens.append(tok)
                self._emit(sess, tok)
            if packed[1, i]:
                self._retire(sess)
        with self._cond:
            self.steps += 1
            self.tokens_out += emitted
            self._rate_tokens += emitted
            rate_t0, rate_tokens = self._rate_t0, self._rate_tokens
        _telemetry.inc("serving.decode.steps.count", model=self.name,
                       replica=self.replica)
        if emitted:
            _telemetry.inc("serving.decode.tokens.count", emitted,
                           model=self.name, replica=self.replica)
        _telemetry.observe("serving.decode.token_latency_seconds", dt,
                           buckets=LATENCY_BUCKETS, model=self.name)
        elapsed = time.monotonic() - rate_t0
        if elapsed >= 0.5:
            _telemetry.set_gauge("serving.decode.tokens_per_sec",
                                 rate_tokens / elapsed, model=self.name,
                                 replica=self.replica)
            with self._cond:
                self._rate_t0 = time.monotonic()
                self._rate_tokens = 0
        self._occupancy_gauge()
        if self._on_step_ok is not None:
            self._on_step_ok()
        return state

    def _fail_all(self, exc, _poisoned_state):
        """A failed device dispatch poisons the donated state: every
        held session gets the error (the batcher's batch-error
        contract), the state restarts from zeros (same shapes — no
        recompile), and the worker survives to serve the queue."""
        _telemetry.inc("serving.error.count", model=self.name)
        with self._cond:
            held = [x for x in self._slot_sessions if x is not None]
            self._slot_sessions = [None] * self.slots
        for sess in held:
            self._finish(sess, error=exc)
        self._occupancy_gauge()
        if self._on_step_error is not None:
            self._on_step_error(exc)
        return self._fresh_state()

    # -- session completion ------------------------------------------------
    def _emit(self, sess, tok):
        if sess.on_token is None:
            return
        try:
            sess.on_token(tok)
        except Exception:  # noqa: broad-except — a client callback must
            # never kill the engine thread; drop the stream, keep result()
            _log.warning("decode: on_token callback of %r failed; "
                         "disabling the stream", self.name, exc_info=True)
            sess.on_token = None

    def _retire(self, sess, error=None):
        with self._cond:
            if sess.slot is not None \
                    and self._slot_sessions[sess.slot] is sess:
                self._slot_sessions[sess.slot] = None
            sess.done_step = self.steps
        self._finish(sess, error=error)

    def _finish(self, sess, error=None):
        with self._cond:
            # idempotent: a forced stop() that timed out its joins can
            # race the still-running worker retiring the same session —
            # the pool's on_done hook must fire exactly once per session
            # or its outstanding accounting drifts
            if sess._finished:
                return
            sess._finished = True
        sess.t_done = time.monotonic()
        if error is not None:
            sess.future.set_error(error)
        else:
            sess.future.set_result(list(sess.tokens))
        if sess._on_done is not None:
            self._safe_done(sess)

    def _safe_done(self, sess):
        try:
            sess._on_done(sess)
        except Exception:  # noqa: broad-except — pool accounting hooks
            # must never kill the engine thread
            _log.warning("decode: on_done hook failed", exc_info=True)

    def _occupancy_gauge(self):
        with self._cond:
            active = sum(1 for x in self._slot_sessions if x is not None)
        _telemetry.set_gauge("serving.decode.slot_occupancy",
                             active / float(self.slots), model=self.name,
                             replica=self.replica)
