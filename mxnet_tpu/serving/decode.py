"""Continuous-batching decode engine — autoregressive serving on slots.

The PR 3 batcher coalesces *fixed-shape* requests; an autoregressive LM
breaks that model: every sequence wants a different number of steps, and
naive batching waits for the slowest sequence while the rest of the
batch pads along dead.  The TPU-native answer is the same move the
sync-free fit loop made for training (docs/how_to/perf.md): make the
decode loop ONE fixed-shape jitted step that never recompiles and never
syncs beyond a single packed host read per token.

:class:`DecodeEngine` owns a device-resident KV cache of fixed shape
``(S slots, max_len)`` per layer and exactly TWO compiled programs:

* **prefill** (one per declared prompt-length bucket): run a
  bucket-padded prompt, scatter its K/V rows into a free slot, sample
  the first token and arm the slot — all in-graph;
* **decode step** (one per ``(S, max_len)``): advance ALL slots one
  token — scatter the incoming token's K/V, attend over each slot's
  ``<= length`` horizon, sample (greedy or temperature), retire
  EOS/length-done slots — returning the packed ``(token, done,
  active)`` buffer whose single host read is the loop's only sync.

**Sampling keys are position-derived, not sequential.**  Each session
carries one host-side ``seed``; the token that will occupy absolute
position ``i`` of the sequence is drawn with
``fold_in(PRNGKey(seed), i)`` — in the prefill (``i = prompt length``)
and in every decode step (``i = length + 1``) alike.  That makes a
session's sample stream a pure function of ``(seed, transcript)``:
independent of which slot it sits in, of its co-resident sessions, and
of how many times it has been interrupted.  The session transcript
(prompt, tokens emitted so far, seed) is therefore a sufficient
checkpoint: re-prefilling ``prompt + generated-so-far`` on ANY replica
resumes the exact stream an uninterrupted run would have produced —
greedy and temperature — which is what
:class:`~mxnet_tpu.serving.pool.ReplicaPool` failover relies on
(docs/serving.md "Session failover & fault domains").

Sequences are admitted into free slots BETWEEN steps (continuous
batching: a late request joins the running batch instead of waiting for
it), retired on EOS/length without recompiling, and stream their tokens
out through per-session callbacks.  Inactive slots ride along at fixed
shape; their scatter rows are unreachable under the attention mask
until a real write replaces them.

The engine is single-device; multi-replica throughput is
:class:`~mxnet_tpu.serving.pool.ReplicaPool`'s job.  The hot loop is
covered by the graftlint host-sync pass (``ci/graftlint``): the packed
per-step read is the one sanctioned transfer.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

import numpy as np

from .. import compile_cache as _compile_cache
from .. import faults as _faults
from .. import perfdebug as _perfdebug
from .. import random as _random
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..base import MXNetError
from ..models import transformer_lm as _tlm
from .batcher import (LATENCY_BUCKETS, DeadlineExceeded, Future,
                      InvalidRequest, Overloaded)
from .kvblocks import KVBlockPool, KVBlocksExhausted

__all__ = ["GenerateSession", "DecodeEngine", "ReplicaKilled",
           "TTFT_BUCKETS"]

_log = logging.getLogger("mxnet_tpu.serving")

#: time-to-first-token histogram bounds (seconds) — first tokens pay a
#: queue wait + one prefill, so the ladder reaches further than the
#: per-token one
TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0)


# shared int-env parser — ONE definition lives in compile_cache.py
# (pool.py imports it from there too)
from ..compile_cache import _env_int  # noqa: E402


class ReplicaKilled(MXNetError):
    """The ``serving.replica.kill`` fault hard-killed this engine
    mid-generation: the engine is permanently closed (a crashed replica
    process, not a transient step fault) and its sessions must migrate
    — the pool treats this as an instant circuit-open."""


class GenerateSession:
    """One streaming generation request: queued -> active(slot) ->
    done/shed (or migrated to another replica in between — the session
    object survives the move).  ``result()`` blocks for the full token
    list (prompt NOT included; EOS, when hit, is the last token);
    ``on_token`` streams each token from the engine thread (must be
    cheap and non-blocking — HTTP streaming hands it a queue put).

    The session IS its own failover checkpoint: ``prompt``, ``tokens``
    (everything generated AND delivered so far — the engine appends
    before it emits, and a failed dispatch emits nothing, so the list
    never runs ahead of or behind the client stream), ``seed`` (the
    position-keyed sampling seed) and ``max_new_tokens`` are exactly
    what a healthy replica needs to resume the stream bit-identically.
    """

    __slots__ = ("prompt", "max_new_tokens", "temperature", "deadline",
                 "on_token", "on_event", "tokens", "future", "seed",
                 "tenant", "migrations", "migrate_t0", "t_submit",
                 "t_first", "t_done", "slot", "admit_step", "done_step",
                 "trace", "_finished", "_lock", "_on_done")

    def __init__(self, prompt, max_new_tokens, temperature, deadline_ms,
                 on_token, on_done=None, seed=0, tenant=None,
                 on_event=None):
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        self.on_token = on_token
        self.on_event = on_event
        self.tokens = []
        self.future = Future()
        self.seed = int(seed) & 0xFFFFFFFF
        self.tenant = tenant
        #: failure-driven migration attempts so far (the pool's retry
        #: budget counts these; version-swap migrations are free)
        self.migrations = 0
        #: failure timestamp of an in-flight migration — the target
        #: engine stamps ``serving.failover.recovery_seconds`` from it
        #: when the re-prefill lands (true failure-to-resumed latency)
        self.migrate_t0 = None
        self.t_submit = time.monotonic()
        self.t_first = None
        self.t_done = None
        self.slot = None
        self.admit_step = None
        self.done_step = None
        #: the session's root span ("serving.generate") — RIDES every
        #: migration with the session, so spans recorded on replica B
        #: after a failover still parent into the same trace.  The
        #: shared no-op span when tracing is off.
        self.trace = _tracing.NULL_SPAN
        self._finished = False
        # session-level lock: completion must stay exactly-once across
        # MIGRATION — engine A's forced stop can race engine B retiring
        # the same (migrated) session, so the flag cannot live under
        # either engine's lock
        self._lock = threading.Lock()
        self._on_done = on_done

    def _resolve(self, error=None):
        """Exactly-once completion: resolve the future and fire the
        pool's on_done hook.  Returns False when the session already
        finished (the caller must then not double-count telemetry)."""
        with self._lock:
            if self._finished:
                return False
            self._finished = True
        self.t_done = time.monotonic()
        # idempotent: shed/migration paths that already ended the span
        # with a more specific status win — this is the fallback close
        self.trace.end(
            "ok" if error is None else
            ("shed" if isinstance(error, (Overloaded, DeadlineExceeded))
             else "error"),
            tokens=len(self.tokens), migrations=self.migrations)
        if error is not None:
            self.future.set_error(error)
        else:
            self.future.set_result(list(self.tokens))
        if self._on_done is not None:
            try:
                self._on_done(self)
            except Exception:  # noqa: broad-except — pool accounting
                # hooks must never kill the resolving thread
                _log.warning("decode: on_done hook failed", exc_info=True)
        return True

    def finished(self):
        """True once the session resolved (result or typed error) — the
        migration path's filter: a session that resolved while waiting
        to migrate must not be re-admitted."""
        with self._lock:
            return self._finished

    def cancel(self):
        """Abandon the request: queued sessions are dropped at the next
        admission scan, an active session is retired (its slot freed) at
        the next step boundary.  Returns False when the session already
        finished.  ONE cancellation flag — the embedded Future's (the
        same machinery the batcher honors), so ``sess.future.cancel()``
        and ``sess.cancel()`` cannot diverge."""
        return self.future.cancel()

    def cancelled(self):
        return self.future.cancelled()

    def done(self):
        return self.future.done()

    def result(self, timeout=60.0):
        """Block for the full generated token list (re-raising the shed
        or dispatch error when the session failed)."""
        return self.future.result(timeout)

    def ttft(self):
        """Time-to-first-token in seconds (None before the first
        token)."""
        return None if self.t_first is None \
            else self.t_first - self.t_submit


class DecodeEngine:
    """Slot-based continuous batching over one
    :mod:`~mxnet_tpu.models.transformer_lm` replica.

    Parameters
    ----------
    cfg : transformer_lm.LMConfig
    params : pytree
        Host or device params; committed to ``device``.
    slots : int
        Concurrent sequences S (``MXNET_DECODE_SLOTS`` default, 8).
        The decode step compiles once per ``(S, max_len)``.
    prefill_buckets : tuple of int
        Declared prompt-length buckets; a prompt pads to the smallest
        bucket that fits (``DynamicBatcher`` bucket idiom — the jit
        cache sees ``len(prefill_buckets)`` prefill shapes, ever).
    max_queue : int
        Admission bound on QUEUED sessions; past it ``submit`` raises
        :class:`Overloaded`.
    device : jax.Device, optional
        Replica placement; defaults to the process default device.
    replica : str
        Telemetry label (``replica=<id>``) — the pool names replicas.
    on_step_error / on_step_ok : callable, optional
        Replica-health hooks (the pool's quarantine counter); called
        outside the engine lock.
    kv_layout : str, optional
        ``"dense"`` (the classic ``(S, max_len)`` per-slot cache) or
        ``"paged"`` (block-table storage through
        :mod:`~mxnet_tpu.serving.kvblocks` — prefix reuse, COW,
        oversubscription).  Defaults to ``MXNET_KV_LAYOUT`` (dense).
        Both layouts produce bit-identical streams for the same
        ``(seed, transcript)``.
    kv_block_size / kv_blocks : int, optional
        Paged sizing overrides (``MXNET_KV_BLOCK_SIZE`` /
        ``MXNET_KV_BLOCKS`` defaults; see kvblocks.py).
    kv_prefix_cache : bool, optional
        Paged prefix reuse toggle (``MXNET_KV_PREFIX_CACHE`` default).
    """

    def __init__(self, cfg, params, *, slots=None, prefill_buckets=(8, 32),
                 max_queue=64, device=None, name="lm", replica="0",
                 autostart=True, on_step_error=None, on_step_ok=None,
                 kv_layout=None, kv_block_size=None, kv_blocks=None,
                 kv_prefix_cache=None):
        import jax

        self.cfg = cfg
        self.name = name
        self.replica = str(replica)
        self.slots = int(slots) if slots is not None \
            else _env_int("MXNET_DECODE_SLOTS", 8)
        if self.slots < 1:
            raise MXNetError("DecodeEngine needs >= 1 slot")
        buckets = tuple(sorted({int(b) for b in prefill_buckets}))
        if not buckets or buckets[0] < 1 or buckets[-1] > cfg.max_len:
            raise MXNetError(
                "prefill buckets %r must be within 1..max_len=%d"
                % (buckets, cfg.max_len))
        self.prefill_buckets = buckets
        self.max_queue = int(max_queue)
        self._device = device if device is not None else jax.devices()[0]
        self._params = jax.device_put(params, self._device)
        self._on_step_error = on_step_error
        self._on_step_ok = on_step_ok
        self._on_migrate = None

        self._cond = threading.Condition(threading.Lock())
        self._queue = deque()
        self._slot_sessions = [None] * self.slots
        self._running = False
        self._draining = False
        self._closed = False
        self._thread = None
        self._beat = time.monotonic()
        #: total decode steps (tests pin continuous admission on it)
        self.steps = 0
        #: total generated tokens
        self.tokens_out = 0
        #: sessions re-admitted here by failover (describe/healthz card)
        self.resumed = 0
        #: prompt+generated tokens re-prefilled for those resumes
        self.reprefilled_tokens = 0
        self._rate_t0 = time.monotonic()
        self._rate_tokens = 0

        layout = kv_layout if kv_layout is not None \
            else (os.environ.get("MXNET_KV_LAYOUT", "dense") or "dense")
        layout = str(layout).strip().lower()
        if layout not in ("dense", "paged"):
            raise MXNetError(
                "kv_layout/MXNET_KV_LAYOUT must be 'dense' or 'paged', "
                "got %r" % layout)
        self.kv_layout = layout
        #: paged storage control plane (None under the dense layout)
        self._kv = KVBlockPool(
            cfg, self.slots, block_size=kv_block_size,
            num_blocks=kv_blocks, prefix_cache=kv_prefix_cache,
            model=name, replica=self.replica) \
            if layout == "paged" else None
        #: host mirror of each slot's device ``lengths`` — the paged
        #: loop derives the next write position (and block-boundary
        #: appends) from it without a device read
        self._slot_len = [0] * self.slots

        self._step_fn = None       # built in _build()
        self._prefill_fns = {}
        self._boot_state = self._build()
        labels = {"model": name, "replica": self.replica}
        _telemetry.inc("serving.decode.sessions.count", 0, **labels)
        _telemetry.inc("serving.decode.tokens.count", 0, **labels)
        _telemetry.inc("serving.decode.steps.count", 0, **labels)
        _telemetry.set_gauge("serving.decode.slot_occupancy", 0.0, **labels)
        _telemetry.set_gauge("serving.decode.tokens_per_sec", 0.0, **labels)
        _telemetry.inc("serving.failover.reprefill_tokens.count", 0,
                       **labels)
        for reason in ("deadline", "overload", "abandoned", "drain",
                       "kv_blocks"):
            _telemetry.inc("serving.shed.count", 0, model=name,
                           reason=reason)
        if autostart:
            self.start()

    # -- compiled programs -------------------------------------------------
    def _build(self):
        """Build the two jitted programs and the initial device state;
        warm-compile every shape so no live request ever eats a trace
        (persistent-cache loads on a warm reload, PR 7)."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        s, m = self.slots, cfg.max_len
        eos = np.int32(cfg.eos_id)

        def fold_key(seed, pos):
            # the ONE key derivation (failover invariant): the token
            # that will occupy absolute position ``pos`` of its
            # sequence is drawn with fold_in(PRNGKey(seed), pos) — a
            # pure function of the session transcript, never of slot
            # index, co-residents, or interruption history
            return jax.random.fold_in(jax.random.PRNGKey(seed), pos)

        def sample(keys, logits, temps):
            # greedy when temperature == 0, else temperature sampling;
            # per-row position-derived keys — the loop never touches
            # the host RNG
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            drawn = jax.vmap(
                lambda kk, lg, tt: jax.random.categorical(
                    kk, lg / jnp.maximum(tt, 1e-6)))(
                        keys, logits, temps).astype(jnp.int32)
            return jnp.where(temps > 0.0, drawn, greedy)

        def finish_step(state_rest, logits, keep):
            # shared sampling/retirement tail of both layouts
            last_tok, lengths, limits, active, temps, seeds = state_rest
            active = active & keep
            # last_tok sits at position ``lengths``; the sampled token
            # will occupy ``lengths + 1``
            keys = jax.vmap(fold_key)(seeds, lengths + 1)
            tok = sample(keys, logits, temps)
            new_len = lengths + active.astype(jnp.int32)
            done = active & ((tok == eos) | (new_len >= limits))
            new_active = active & ~done
            new_last = jnp.where(active, tok, last_tok)
            packed = jnp.stack([jnp.where(active, tok, -1),
                                done.astype(jnp.int32),
                                new_active.astype(jnp.int32)])
            return (new_last, new_len, limits, new_active, temps,
                    seeds), packed

        def arm_slot(state_rest, slot, tok_logits, length, limit, temp,
                     seed, activate):
            # shared slot-arming tail of both prefill layouts: the
            # prompt holds positions 0..length-1; the sampled token
            # occupies ``length`` — on a failover re-prefill of
            # prompt+generated this is exactly the key the interrupted
            # replica's next decode step would have used
            last_tok, lengths, limits, active, temps, seeds = state_rest
            tok = sample(fold_key(seed, length)[None], tok_logits[None],
                         jnp.full((1,), temp))[0]
            first_done = (tok == eos) | (limit <= length)
            arm = activate & ~first_done
            last_tok = last_tok.at[slot].set(tok)
            lengths = lengths.at[slot].set(length)
            limits = limits.at[slot].set(limit)
            temps = temps.at[slot].set(temp)
            active = active.at[slot].set(arm)
            seeds = seeds.at[slot].set(seed)
            out = jnp.stack([tok, first_done.astype(jnp.int32)])
            return (last_tok, lengths, limits, active, temps, seeds), out

        if self._kv is not None:
            nb, bs = self._kv.num_blocks, self._kv.block_size

            def step(params, state, keep, tables):
                pool_k, pool_v = state[0], state[1]
                logits, pool_k, pool_v = _tlm.decode_step_paged(
                    cfg, params, pool_k, pool_v, tables, state[2],
                    state[3])
                rest, packed = finish_step(state[2:], logits, keep)
                return (pool_k, pool_v) + rest, packed

            def prefill(params, state, tokens, start, length, slot,
                        table, limit, temp, seed, activate, cow_src,
                        cow_dst):
                pool_k, pool_v = state[0], state[1]
                # admission-time copy-on-write: duplicate the shared
                # partial tail block before the suffix scatters into
                # the copy; (0, 0) — scratch onto itself — is the
                # no-COW case, so ONE compiled program covers cold,
                # prefix-hit and COW admissions alike
                pool_k = tuple(pk.at[cow_dst].set(pk[cow_src])
                               for pk in pool_k)
                pool_v = tuple(pv.at[cow_dst].set(pv[cow_src])
                               for pv in pool_v)
                last_logits, pool_k, pool_v = _tlm.prefill_kv_paged(
                    cfg, params, pool_k, pool_v, table, tokens, start,
                    length)
                rest, out = arm_slot(state[2:], slot, last_logits,
                                     length, limit, temp, seed, activate)
                return (pool_k, pool_v) + rest, out

            self._step_fn = self._instrument(
                jax.jit(step, donate_argnums=(1,)), "decode_step",
                ("decode_step_paged", s, m, nb, bs))
            pf_jit = jax.jit(prefill, donate_argnums=(1,))
            self._prefill_fns = {
                b: self._instrument(pf_jit, "decode_prefill",
                                    ("decode_prefill_paged", b, s, m,
                                     nb, bs))
                for b in self.prefill_buckets}
        else:
            def step(params, state, keep):
                cache_k, cache_v = state[0], state[1]
                logits, cache_k, cache_v = _tlm.decode_step_math(
                    cfg, params, cache_k, cache_v, state[2], state[3])
                rest, packed = finish_step(state[2:], logits, keep)
                return (cache_k, cache_v) + rest, packed

            def prefill(params, state, tokens, length, slot, limit,
                        temp, seed, activate):
                cache_k, cache_v = state[0], state[1]
                last_logits, ks, vs = _tlm.prefill_kv(cfg, params,
                                                      tokens, length)
                cache_k = tuple(
                    jax.lax.dynamic_update_slice(ck, k[None],
                                                 (slot, 0, 0, 0))
                    for ck, k in zip(cache_k, ks))
                cache_v = tuple(
                    jax.lax.dynamic_update_slice(cv, v[None],
                                                 (slot, 0, 0, 0))
                    for cv, v in zip(cache_v, vs))
                rest, out = arm_slot(state[2:], slot, last_logits,
                                     length, limit, temp, seed, activate)
                return (cache_k, cache_v) + rest, out

            self._step_fn = self._instrument(
                jax.jit(step, donate_argnums=(1,)), "decode_step",
                ("decode_step", s, m))
            pf_jit = jax.jit(prefill, donate_argnums=(1,))
            self._prefill_fns = {
                b: self._instrument(pf_jit, "decode_prefill",
                                    ("decode_prefill", b, s, m))
                for b in self.prefill_buckets}

        state = self._fresh_state()
        with _compile_cache.recording_scope() as rec:
            cc0 = _compile_cache.stats() if _compile_cache.enabled() \
                else None
            state = self._warm(state)
            cc1 = _compile_cache.stats() if cc0 is not None else None
        self.warmup_entries = rec.entries
        if cc0 is not None:
            # a separate family from the batcher's serving.warmup.* —
            # this one carries a replica label, and a telemetry family
            # must never mix label sets
            _telemetry.set_gauge(
                "serving.decode.warmup.cold_compiles",
                cc1["misses"] - cc0["misses"], model=self.name,
                replica=self.replica)
            _telemetry.set_gauge(
                "serving.decode.warmup.cache_loads",
                cc1["hits"] - cc0["hits"], model=self.name,
                replica=self.replica)
        _telemetry.event("serving.decode.warm", model=self.name,
                         replica=self.replica, slots=s,
                         buckets=len(self.prefill_buckets))
        return state

    def _instrument(self, fn, kind, build_kind):
        """First-call hook: count the compile (``xla.compile.count``,
        the recompile-detector's family) and record the build into the
        PR 7 warm-up manifest registry."""
        def hook(f, args, kwargs, dt):
            _telemetry.inc("xla.compile.count", kind=kind)
            _telemetry.inc("xla.compile.seconds", dt, kind=kind)
            if _compile_cache.recording():
                _compile_cache.note_build(
                    "serving:%s" % self.name, build_kind, f.lower, args,
                    kwargs, dt)
        return _perfdebug.first_call_hook(fn, hook)

    def _fresh_state(self):
        """Zeroed device-resident slot state, committed to the replica
        device.  Under the paged layout the K/V tensors are the BLOCK
        POOLS, and rebuilding them from zeros invalidates every block —
        the host control plane (allocator, tables, prefix cache) resets
        in the same breath."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        s = self.slots
        hd = cfg.embed // cfg.heads
        if self._kv is not None:
            self._kv.reset()
            kv_shape = (self._kv.num_blocks, self._kv.block_size,
                        cfg.heads, hd)
        else:
            kv_shape = (s, cfg.max_len, cfg.heads, hd)
        self._slot_len = [0] * s
        state = (tuple(jnp.zeros(kv_shape, jnp.float32)
                       for _ in range(cfg.layers)),
                 tuple(jnp.zeros(kv_shape, jnp.float32)
                       for _ in range(cfg.layers)),
                 jnp.zeros((s,), jnp.int32),        # last_tok
                 jnp.zeros((s,), jnp.int32),        # lengths
                 jnp.zeros((s,), jnp.int32),        # limits
                 jnp.zeros((s,), bool),             # active
                 jnp.zeros((s,), jnp.float32),      # temps
                 jnp.zeros((s,), jnp.uint32))       # per-slot seeds
        return jax.device_put(state, self._device)

    def _warm(self, state):
        """Compile the decode step and every prefill bucket against the
        real state buffers — ``activate=False`` leaves the slots
        disarmed, so warm-up never corrupts serving state.  The paged
        warm-up runs with an all-zero table: every scatter lands in the
        scratch block, which is exactly what makes it harmless."""
        if self._kv is not None:
            mb = self._kv.max_blocks
            ztab = np.zeros((mb,), np.int32)
            for b in self.prefill_buckets:
                state, _out = self._prefill_fns[b](
                    self._params, state, np.zeros((b,), np.int32),
                    np.int32(0), np.int32(1), np.int32(0), ztab,
                    np.int32(0), np.float32(0.0), np.uint32(0),
                    np.bool_(False), np.int32(0), np.int32(0))
            state, _packed = self._step_fn(
                self._params, state, np.ones((self.slots,), bool),
                np.zeros((self.slots, mb), np.int32))
            return state
        for b in self.prefill_buckets:
            state, _out = self._prefill_fns[b](
                self._params, state, np.zeros((b,), np.int32),
                np.int32(1), np.int32(0), np.int32(0), np.float32(0.0),
                np.uint32(0), np.bool_(False))
        state, _packed = self._step_fn(self._params, state,
                                       np.ones((self.slots,), bool))
        return state

    def set_health_hooks(self, on_error=None, on_ok=None,
                         on_migrate=None):
        """Install the pool's replica-health hooks (and its failover
        hand-off: ``on_migrate(sessions, exc)`` receives the sessions a
        failed dispatch was holding INSTEAD of them being shed — the
        pool re-admits them elsewhere or sheds typed).  Call before
        :meth:`start` — plain attribute flips, deliberately outside the
        engine lock (the hooks take the POOL's lock; holding both here
        would order the locks both ways)."""
        self._on_step_error = on_error
        self._on_step_ok = on_ok
        self._on_migrate = on_migrate

    def rewarm(self):
        """Recompile/reload every program (the pool's quarantine
        re-warm): with the persistent compile cache armed this is pure
        cache loads — zero cold compiles on a healthy host.  Refuses a
        running or CLOSED engine — a background re-warm racing a
        pointer-flip version swap must not resurrect the retired
        replica (the pool's except path leaves it quarantined)."""
        with self._cond:
            if self._running:
                raise MXNetError("rewarm() needs a stopped engine")
            if self._closed:
                raise MXNetError("decode engine %r is closed"
                                 % self.name)
        state = self._build()
        with self._cond:
            self._boot_state = state
            self._draining = False

    # -- client side -------------------------------------------------------
    def _validate_admission(self, n, what):
        """THE transcript-length admission validator — ``submit`` and
        ``resume`` used to carry drifting copies of the same two
        checks; they now share this one, which also enforces the paged
        block budget.  ``n`` is the transcript length that will be
        (re-)prefilled; ``what`` names it in the client's error.
        Raises :class:`InvalidRequest` for transcripts no engine of
        this shape could ever hold, and typed
        :class:`KVBlocksExhausted` (an :class:`Overloaded` — clients
        retry it) when the block pool is sized too small for the
        transcript even with every block free."""
        if n > self.prefill_buckets[-1]:
            raise InvalidRequest(
                "%s of %d tokens exceeds the largest prefill bucket %d"
                % (what, n, self.prefill_buckets[-1]))
        if n >= self.cfg.max_len:
            raise InvalidRequest(
                "%s of %d tokens leaves no room under max_len=%d"
                % (what, n, self.cfg.max_len))
        if self._kv is not None and not self._kv.admissible(n):
            _telemetry.inc("serving.shed.count", model=self.name,
                           reason="kv_blocks")
            raise KVBlocksExhausted(
                "%s of %d tokens needs %d KV blocks but the pool holds "
                "only %d allocatable (%d blocks x %d tokens)"
                % (what, n, n // self._kv.block_size + 1,
                   self._kv.num_blocks - 1, self._kv.num_blocks,
                   self._kv.block_size))

    def submit(self, prompt, *, max_new_tokens=16, temperature=0.0,
               deadline_ms=None, on_token=None, on_done=None, seed=None,
               tenant=None, on_event=None):
        """Queue a generation request; returns its
        :class:`GenerateSession`.  Raises :class:`Overloaded` past the
        queue bound and :class:`InvalidRequest` for malformed prompts
        (the client's error, surfaced at submit).

        ``seed`` pins the session's sampling stream (temperature
        replays and cross-replica failover are bit-identical for the
        same seed); None draws one from :mod:`mxnet_tpu.random`, so
        ``mx.random.seed(n)`` still makes single-stream runs
        reproducible end to end."""
        prompt = np.array(prompt, np.int32).ravel()
        if prompt.size < 1:
            raise InvalidRequest("empty prompt")
        self._validate_admission(int(prompt.size), "prompt")
        if prompt.min() < 0 or prompt.max() >= self.cfg.vocab:
            raise InvalidRequest(
                "prompt token ids must be in 0..vocab-1=%d"
                % (self.cfg.vocab - 1))
        if int(max_new_tokens) < 1:
            raise InvalidRequest("max_new_tokens must be >= 1")
        if float(temperature) < 0:
            raise InvalidRequest("temperature must be >= 0")
        if seed is None:
            seed = int(np.asarray(_random.next_key())[0])  # lint: ok[host-sync] tiny submit-time key-material read (one uint32 per session), not the per-step hot loop
        sess = GenerateSession(prompt, max_new_tokens, temperature,
                               deadline_ms, on_token, on_done, seed=seed,
                               tenant=tenant, on_event=on_event)
        # root span for the session's whole lifetime — opened on the
        # CALLER's thread so it parents under any in-flight request
        # span (HTTP handler, batcher); stack=False because it outlives
        # this call and is closed from the engine thread at _resolve
        sess.trace = _tracing.start_span(
            "serving.generate", stack=False, model=self.name,
            prompt_tokens=int(prompt.size))
        with self._cond:
            if self._closed:
                sess.trace.end("error", reason="closed")
                raise MXNetError("decode engine %r is closed" % self.name)
            if self._draining:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="drain")
                sess.trace.end("shed", reason="drain")
                raise Overloaded("decode engine %r is draining"
                                 % self.name)
            if len(self._queue) >= self.max_queue:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="overload")
                sess.trace.end("shed", reason="overload")
                raise Overloaded(
                    "decode engine %r overloaded: %d sessions queued"
                    % (self.name, len(self._queue)))
            # counted AFTER admission: sessions.count is accepted
            # sessions (completion/shed ratios read against it);
            # rejected submits show only in serving.shed.count
            _telemetry.inc("serving.decode.sessions.count",
                           model=self.name, replica=self.replica)
            self._queue.append(sess)
            self._cond.notify()
        return sess

    def generate(self, prompt, timeout=60.0, **kw):
        """Blocking convenience: ``submit`` + ``result``."""
        sess = self.submit(prompt, **kw)
        try:
            return sess.result(timeout)
        except DeadlineExceeded:
            sess.cancel()
            raise

    def resume(self, sess):
        """Re-admit a migrated session (the pool's failover path): its
        transcript — ``prompt + tokens generated so far`` — is
        re-prefilled into a free slot and decoding continues with the
        same position-derived keys, so the resumed stream is
        bit-identical to what the interrupted replica would have
        produced.  Raises :class:`InvalidRequest` when the combined
        transcript no longer fits a prefill bucket (the caller sheds
        typed) and the engine's closed/draining errors otherwise.

        Deliberately NOT bounded by ``max_queue``: the session already
        holds pool admission (its accounting moved with it) — bouncing
        a migration off the queue bound would turn a survivable replica
        loss into a shed.  Resumed sessions jump the queue: they have
        already waited once."""
        full = int(sess.prompt.size) + len(sess.tokens)
        self._validate_admission(
            full, "migrated transcript (prompt %d + generated %d)"
            % (sess.prompt.size, len(sess.tokens)))
        with self._cond:
            if self._closed:
                raise MXNetError("decode engine %r is closed" % self.name)
            if self._draining:
                raise Overloaded("decode engine %r is draining"
                                 % self.name)
            # not counted under sessions.count — the session was
            # counted at its original admission
            self._queue.appendleft(sess)
            self._cond.notify()
        return sess

    # -- introspection -----------------------------------------------------
    def pending_rows(self):
        """Queued plus active sessions — the graceful-drain quiescence
        probe (one row == one sequence)."""
        with self._cond:
            return len(self._queue) + \
                sum(1 for x in self._slot_sessions if x is not None)

    def outstanding(self):
        """Same number the pool's least-outstanding routing reads."""
        return self.pending_rows()

    def heartbeat_age(self):
        """Seconds since the serve loop last proved liveness, or None
        when no worker has been started.  The loop stamps every
        iteration (idle included), so a stale age means a wedged
        dispatch or a dead worker thread — the fleet controller's
        per-replica liveness probe."""
        with self._cond:
            if self._thread is None:
                return None
            return time.monotonic() - self._beat

    def describe(self):
        with self._cond:
            active = sum(1 for x in self._slot_sessions if x is not None)
            queued = len(self._queue)
            steps = self.steps
            tokens = self.tokens_out
            resumed = self.resumed
            reprefilled = self.reprefilled_tokens
        if self._kv is not None:
            kv = self._kv.describe()
        else:
            hd = self.cfg.embed // self.cfg.heads
            kv = {"layout": "dense",
                  "hbm_bytes": (2 * self.cfg.layers * self.slots
                                * self.cfg.max_len * self.cfg.heads
                                * hd * 4)}
        return {"name": self.name, "kind": "generate",
                "version": getattr(self, "version", None),
                "replica": self.replica, "device": str(self._device),
                "slots": self.slots, "active": active, "queued": queued,
                "steps": steps, "tokens": tokens,
                "sessions_resumed": resumed,
                "reprefilled_tokens": reprefilled,
                "prefill_buckets": list(self.prefill_buckets),
                "max_len": self.cfg.max_len, "kv": kv}

    # -- worker ------------------------------------------------------------
    def start(self):
        with self._cond:
            if self._closed:
                # a closed engine stays closed: restarting its worker
                # (e.g. a stale re-warm thread) would leak a spinning
                # daemon on a servable nobody routes to
                raise MXNetError("decode engine %r is closed"
                                 % self.name)
            if self._thread is not None:
                return self
            if self._boot_state is None:
                # restart after a plain stop(): the compiled programs
                # survive, only the slot state was consumed — rebuild
                # it from zeros (device_put, no recompile)
                self._boot_state = self._fresh_state()
            self._draining = False
            self._running = True
            self._thread = threading.Thread(
                target=self._serve_loop,
                name="decode-%s-%s" % (self.name, self.replica),
                daemon=True)
            self._thread.start()
        return self

    def stop(self, drain=True, deadline=None, hand_off=None):
        """Stop the engine.  ``drain=True`` keeps stepping until every
        ACTIVE sequence finishes (new admissions stop; queued sessions
        are shed immediately with a typed error) under ``deadline``
        seconds (``MXNET_PREEMPT_DRAIN_DEADLINE``, default 30); past
        the deadline — or with ``drain=False`` — unfinished sessions
        are shed, never silently dropped.  ``hand_off`` (a callable
        taking a session list) is the failover alternative to
        shedding: queued and slot-holding sessions are handed over
        intact for the pool to re-admit elsewhere (quarantine takeover,
        version-swap straggler migration) and do not mark the stop
        unclean.  Returns True when the stop lost nothing."""
        if deadline is None:
            deadline = float(os.environ.get(
                "MXNET_PREEMPT_DRAIN_DEADLINE", "30") or 30)
        shed = []
        with self._cond:
            self._draining = True
            if not drain:
                self._running = False
            while self._queue:
                shed.append(self._queue.popleft())
            self._cond.notify_all()
        err = MXNetError("decode engine %r stopped before this session "
                         "was served" % self.name)
        clean = not shed or hand_off is not None
        if hand_off is not None and shed:
            hand_off(shed)
        else:
            for sess in shed:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="drain")
                self._finish(sess, error=err)
        with self._cond:
            t, self._thread = self._thread, None
        worker_dead = True
        if t is not None:
            t.join(timeout=deadline if drain else 5.0)
            if t.is_alive():
                clean = False
                with self._cond:
                    self._running = False
                    self._cond.notify_all()
                t.join(timeout=10.0)
            worker_dead = not t.is_alive()
        # anything still holding a slot is handed off or shed typed —
        # but hand-off REQUIRES the worker to be provably gone: a
        # wedged dispatch that eventually returns would keep appending
        # tokens to a session another replica now owns, corrupting the
        # stream.  The shed path stays safe either way (idempotent
        # session-level resolve).
        leftovers = []
        with self._cond:
            for i, sess in enumerate(self._slot_sessions):
                if sess is not None:
                    leftovers.append(sess)
                    self._slot_sessions[i] = None
        if hand_off is not None and leftovers and worker_dead:
            hand_off(leftovers)
        else:
            for sess in leftovers:
                clean = False
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="drain")
                self._finish(sess, error=err)
        self._occupancy_gauge()
        return clean

    def close(self, drain=True):
        """Permanent :meth:`stop`: further submits fail fast."""
        with self._cond:
            self._closed = True
        return self.stop(drain=drain)

    def _serve_loop(self):
        with self._cond:
            state = self._boot_state
            self._boot_state = None
        while True:
            admits = []
            shed = []  # (session, reason) — finished OUTSIDE the lock:
            # _finish runs the pool's on_done hook, which takes the POOL
            # lock, and pool.describe() takes pool-then-engine — holding
            # the engine lock here would order the locks both ways
            with self._cond:
                if not self._running:
                    return
                # liveness heartbeat: stamped every loop iteration (the
                # idle wait below is 20ms, so an IDLE engine still beats)
                # — only a wedged dispatch or a dead worker goes stale.
                # The fleet controller's per-replica supervision reads it
                # through heartbeat_age().
                self._beat = time.monotonic()
                free = [i for i, x in enumerate(self._slot_sessions)
                        if x is None]
                # walk the WHOLE queue every iteration: abandoned or
                # expired entries must release the max_queue admission
                # bound (and the pool's outstanding accounting) even
                # while every slot is busy — the batcher's abandoned-
                # entry fix, applied here too.  FIFO order preserved.
                now = time.monotonic()
                keep = deque()
                while self._queue:
                    sess = self._queue.popleft()
                    if sess.cancelled():
                        shed.append((sess, "abandoned"))
                    elif sess.deadline is not None \
                            and now > sess.deadline:
                        shed.append((sess, "deadline"))
                    elif free:
                        sess.slot = free.pop(0)
                        self._slot_sessions[sess.slot] = sess
                        admits.append(sess)
                    else:
                        keep.append(sess)
                self._queue = keep
                have_active = any(x is not None
                                  for x in self._slot_sessions)
                if not admits and not shed and not have_active:
                    if self._draining:
                        self._running = False
                        return
                    self._cond.wait(0.02)
                    continue
            for sess, reason in shed:
                # every exit path resolves the future and fires on_done
                # — a dropped session would leak the pool's outstanding
                # accounting forever
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason=reason)
                err = DeadlineExceeded("deadline expired while queued "
                                       "for a decode slot") \
                    if reason == "deadline" else \
                    MXNetError("session abandoned by the client while "
                               "queued")
                sess.trace.end("shed", reason=reason, where="queued")
                self._finish(sess, error=err)
            for sess in admits:
                state, aborted = self._admit(sess, state)
                if aborted:
                    # _fail_all already resolved EVERY reserved slot —
                    # including admits not yet prefilled; touching them
                    # again would double-fire the pool's on_done hook
                    break
            with self._cond:
                have_active = any(x is not None
                                  for x in self._slot_sessions)
            if have_active:
                state = self._step(state)

    def _admit(self, sess, state):
        """Prefill ``sess`` into its (already reserved) slot: one
        bucket-shaped dispatch + one tiny admission-time host read for
        the first token (TTFT); the hot loop's own budget is untouched.
        A migrated session re-prefills its whole transcript (prompt +
        generated-so-far) — the ``limit`` stays derived from the
        ORIGINAL prompt length, so total generation length is unchanged
        by any number of migrations.  Returns ``(state, aborted)`` —
        aborted=True means the dispatch poisoned the donated state and
        :meth:`_fail_all` already resolved every held session."""
        cfg = self.cfg
        p0 = int(sess.prompt.size)
        resumed = len(sess.tokens) > 0
        if resumed:
            gen = np.asarray(sess.tokens, np.int32)  # lint: ok[host-sync] host-list -> ndarray conversion of the transcript, no device value involved
            full = np.concatenate([sess.prompt, gen])
        else:
            full = sess.prompt
        n = int(full.size)
        limit = np.int32(min(p0 + sess.max_new_tokens - 1, cfg.max_len))
        if self._kv is not None:
            try:
                plan = self._kv.admit(sess.slot, full)
            except Overloaded as e:
                # typed KV shed: even evicting the prefix cache cannot
                # cover this transcript right now — nothing was
                # dispatched (state unpoisoned, no blocks held), the
                # session sheds typed and the engine keeps serving
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="kv_blocks")
                sess.trace.end("shed", reason="kv_blocks",
                               where="admit")
                self._retire(sess, error=e)
                self._occupancy_gauge()
                return state, False
            # prefix-hit admissions re-/prefill ONLY the unshared
            # suffix: the bucket is chosen by suffix length, so a long
            # shared prompt rides a small prefill program
            suffix = n - plan.start
            bucket = next(b for b in self.prefill_buckets
                          if suffix <= b)
            tokens = np.zeros((bucket,), np.int32)
            tokens[:suffix] = full[plan.start:]
        else:
            plan = None
            bucket = next(b for b in self.prefill_buckets if n <= b)
            tokens = np.zeros((bucket,), np.int32)
            tokens[:n] = full
        # runs on the ENGINE thread: parent explicitly off the session
        # root (the thread-local stack belongs to whoever submitted)
        asp = _tracing.start_span("serving.admit", parent=sess.trace,
                                  stack=False, replica=self.replica,
                                  resumed=resumed, bucket=bucket)
        try:
            if plan is not None:
                state, out = self._prefill_fns[bucket](
                    self._params, state, tokens, np.int32(plan.start),
                    np.int32(n), np.int32(sess.slot),
                    np.ascontiguousarray(self._kv.tables[sess.slot]),
                    limit, np.float32(sess.temperature),
                    np.uint32(sess.seed), np.bool_(True),
                    np.int32(plan.cow_src), np.int32(plan.cow_dst))
            else:
                state, out = self._prefill_fns[bucket](
                    self._params, state, tokens, np.int32(n),
                    np.int32(sess.slot), limit,
                    np.float32(sess.temperature), np.uint32(sess.seed),
                    np.bool_(True))
            out = np.asarray(out)  # lint: ok[host-sync] admission-time first-token read (TTFT), not the per-step hot loop
        except Exception as e:
            # a poisoned prefill poisons the whole donated state: fail
            # every session this engine holds and restart from zeros
            # (the queue is untouched)
            asp.end("error", error=type(e).__name__)
            return self._fail_all(e, state), True
        if plan is not None:
            self._slot_len[sess.slot] = n
            # index the (now device-resident) prompt prefix for future
            # admissions — insertion AFTER a successful dispatch only
            self._kv.offer(sess.slot, sess.prompt)
        asp.end("ok", reprefilled=n if resumed else 0,
                prefix_reused=plan.reused_tokens if plan else 0)
        now = time.monotonic()
        tok = int(out[0])
        sess.tokens.append(tok)
        self._emit(sess, tok)
        if sess.t_first is None:
            # TTFT is first token EVER — a migrated session already
            # paid (and recorded) its first-token latency
            sess.t_first = now
            _telemetry.observe("serving.decode.ttft_seconds",
                               sess.t_first - sess.t_submit,
                               buckets=TTFT_BUCKETS, model=self.name)
        _telemetry.inc("serving.decode.tokens.count", model=self.name,
                       replica=self.replica)
        with self._cond:
            sess.admit_step = self.steps
            self.tokens_out += 1
            self._rate_tokens += 1
            if resumed:
                self.resumed += 1
                self.reprefilled_tokens += n
        if resumed:
            _telemetry.inc("serving.failover.reprefill_tokens.count", n,
                           model=self.name, replica=self.replica)
            if sess.migrate_t0 is not None:
                # failure-to-resumed: stamped when the session left its
                # failed replica, observed when it is DECODING again —
                # queue wait and re-prefill included
                _telemetry.observe("serving.failover.recovery_seconds",
                                   now - sess.migrate_t0,
                                   model=self.name)
                sess.migrate_t0 = None
        if out[1]:  # EOS or max_new_tokens == 1: done at prefill
            self._retire(sess)
        self._occupancy_gauge()
        return state, False

    def _step(self, state):
        """ONE fixed-shape decode dispatch for all slots + the single
        packed host read; host bookkeeping fans tokens out to sessions."""
        keep = np.ones((self.slots,), bool)
        with self._cond:
            sessions = list(self._slot_sessions)
        now = time.monotonic()
        for i, sess in enumerate(sessions):
            if sess is None:
                continue
            if sess.cancelled():
                keep[i] = False
            elif sess.deadline is not None and now > sess.deadline:
                keep[i] = False
        if self._kv is not None:
            # block-boundary appends: the step scatters each live
            # slot's K/V at position ``lengths`` — make sure that
            # block exists BEFORE dispatch.  A dry pool (even after
            # prefix-cache eviction) sheds the session typed instead
            # of corrupting a shared scratch row.
            for i, sess in enumerate(sessions):
                if sess is None or not keep[i]:
                    continue
                try:
                    self._kv.append(i, min(self._slot_len[i],
                                           self.cfg.max_len - 1))
                except Overloaded as e:
                    keep[i] = False
                    sessions[i] = None
                    _telemetry.inc("serving.shed.count",
                                   model=self.name, reason="kv_blocks")
                    sess.trace.end("shed", reason="kv_blocks",
                                   where="active")
                    self._retire(sess, error=e)
        t0 = time.perf_counter()
        try:
            if _faults.should_fire("serving.decode"):
                raise _faults.FaultInjected(
                    "fault 'serving.decode': decode step of model %r "
                    "killed" % self.name)
            if _faults.should_fire("serving.replica.kill"):
                # a hard replica death, not a transient step fault: the
                # engine closes permanently (the worker exits, submits
                # fail fast, rewarm refuses) and every held session
                # goes down the migration path
                with self._cond:
                    self._closed = True
                    self._running = False
                raise ReplicaKilled(
                    "fault 'serving.replica.kill': replica %s of model "
                    "%r hard-killed mid-generation"
                    % (self.replica, self.name))
            if self._kv is not None:
                # the tables ride along as a tiny int32 H2D argument —
                # fixed shape, no recompile, not a device read
                state, packed = self._step_fn(
                    self._params, state, keep,
                    np.ascontiguousarray(self._kv.tables))
            else:
                state, packed = self._step_fn(self._params, state, keep)
            packed = np.asarray(packed)  # lint: ok[host-sync] THE one sanctioned host read per decode step (packed token/done/active buffer)
        except Exception as e:
            return self._fail_all(e, state)
        dt = time.perf_counter() - t0
        emitted = 0
        for i, sess in enumerate(sessions):
            if sess is None:
                continue
            if not keep[i]:
                reason = "abandoned" if sess.cancelled() else "deadline"
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason=reason)
                err = DeadlineExceeded("session deadline expired "
                                       "mid-generation") \
                    if reason == "deadline" else \
                    MXNetError("session abandoned by the client")
                sess.trace.end("shed", reason=reason, where="active")
                self._retire(sess, error=err)
                continue
            tok = int(packed[0, i])
            if tok >= 0:
                emitted += 1
                sess.tokens.append(tok)
                self._emit(sess, tok)
                # host mirror of the device ``lengths`` advance
                # (new_len = lengths + active): the next step's write
                # position for this slot
                self._slot_len[i] += 1
            if packed[1, i]:
                self._retire(sess)
        with self._cond:
            self.steps += 1
            self.tokens_out += emitted
            self._rate_tokens += emitted
            rate_t0, rate_tokens = self._rate_t0, self._rate_tokens
        _telemetry.inc("serving.decode.steps.count", model=self.name,
                       replica=self.replica)
        if emitted:
            _telemetry.inc("serving.decode.tokens.count", emitted,
                           model=self.name, replica=self.replica)
        _telemetry.observe("serving.decode.token_latency_seconds", dt,
                           buckets=LATENCY_BUCKETS, model=self.name)
        elapsed = time.monotonic() - rate_t0
        if elapsed >= 0.5:
            _telemetry.set_gauge("serving.decode.tokens_per_sec",
                                 rate_tokens / elapsed, model=self.name,
                                 replica=self.replica)
            with self._cond:
                self._rate_t0 = time.monotonic()
                self._rate_tokens = 0
        self._occupancy_gauge()
        if self._on_step_ok is not None:
            self._on_step_ok()
        return state

    def _fail_all(self, exc, _poisoned_state):
        """A failed device dispatch poisons the donated state: every
        held session is handed to the pool's migration hook (or, with
        no pool above, gets the error — the batcher's batch-error
        contract), the state restarts from zeros (same shapes — no
        recompile), and the worker survives to serve the queue (unless
        a :class:`ReplicaKilled` closed it)."""
        _telemetry.inc("serving.error.count", model=self.name)
        with self._cond:
            held = [x for x in self._slot_sessions if x is not None]
            self._slot_sessions = [None] * self.slots
        # health first: the pool quarantines/opens the circuit BEFORE
        # the migration hook picks a target, so a failing replica does
        # not re-admit its own casualties
        if self._on_step_error is not None:
            self._on_step_error(exc)
        if held:
            migrate = self._on_migrate
            if migrate is not None:
                try:
                    migrate(held, exc)
                except Exception:  # noqa: broad-except — a broken
                    # migration hook must not silently drop sessions:
                    # fall back to the typed batch error
                    _log.warning("decode: migration hook failed; "
                                 "shedding held sessions",
                                 exc_info=True)
                    for sess in held:
                        self._finish(sess, error=exc)
            else:
                for sess in held:
                    self._finish(sess, error=exc)
        self._occupancy_gauge()
        return self._fresh_state()

    # -- session completion ------------------------------------------------
    def _emit(self, sess, tok):
        if sess.on_token is None:
            return
        try:
            sess.on_token(tok)
        except Exception:  # noqa: broad-except — a client callback must
            # never kill the engine thread; drop the stream, keep result()
            _log.warning("decode: on_token callback of %r failed; "
                         "disabling the stream", self.name, exc_info=True)
            sess.on_token = None

    def _retire(self, sess, error=None):
        freed_slot = None
        with self._cond:
            if sess.slot is not None \
                    and self._slot_sessions[sess.slot] is sess:
                self._slot_sessions[sess.slot] = None
                freed_slot = sess.slot
            sess.done_step = self.steps
        if freed_slot is not None and self._kv is not None:
            # outside the engine lock (the allocator has its own); the
            # slot cannot be re-admitted concurrently — admissions run
            # on this same engine thread
            self._kv.release(freed_slot)
        self._finish(sess, error=error)

    def _finish(self, sess, error=None):
        # idempotent ACROSS ENGINES: a forced stop() that timed out its
        # joins can race the still-running worker — or, after a
        # migration, a different engine entirely — retiring the same
        # session; the session's own lock makes the pool's on_done hook
        # fire exactly once either way
        sess._resolve(error=error)

    def _occupancy_gauge(self):
        with self._cond:
            active = sum(1 for x in self._slot_sessions if x is not None)
        _telemetry.set_gauge("serving.decode.slot_occupancy",
                             active / float(self.slots), model=self.name,
                             replica=self.replica)
        if self._kv is not None:
            self._kv.note_sessions(active)
