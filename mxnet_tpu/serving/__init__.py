"""Serving subsystem — a throughput-oriented model server over the
single-request :class:`~mxnet_tpu.predict.Predictor` plus a
continuous-batching autoregressive tier.

Seven layers (see ``docs/serving.md``):

* :mod:`~mxnet_tpu.serving.batcher` — dynamic micro-batching with
  shape-bucket padding, per-request deadlines, and typed
  :class:`Overloaded` admission control;
* :mod:`~mxnet_tpu.serving.decode` — slot-based continuous batching
  for autoregressive LMs: one fixed-shape jitted decode step, one
  packed host read per token, mid-flight admission into free slots;
* :mod:`~mxnet_tpu.serving.kvblocks` — the paged KV memory subsystem
  under the decode tier: device block pools, a refcounting
  :class:`BlockAllocator`, per-slot block tables and a hash-keyed
  prefix cache with admission-time copy-on-write;
* :mod:`~mxnet_tpu.serving.pool` — N routed replicas over
  ``jax.devices()``: weighted least-outstanding routing, per-tenant
  quotas, priority shedding, per-replica circuit breakers
  (closed/open/half-open + background re-warm), and session failover —
  mid-generation migration with per-tenant retry budgets;
* :mod:`~mxnet_tpu.serving.registry` — versioned multi-model registry
  with atomic publish (checksummed manifest-last), atomic reload,
  per-bucket warm-up compilation, and pointer-flip ``register`` swaps
  of off-registry-built servables (pools included);
* :mod:`~mxnet_tpu.serving.controller` — the fleet control plane: a
  :class:`FleetController` closed loop (SLO-driven autoscaling with
  hysteresis + cooldown, :class:`DeviceFleet` bin-packing placement
  and rebalancing, per-replica supervision under restart budgets,
  priority shedding when the fleet is exhausted);
* :mod:`~mxnet_tpu.serving.frontend` — in-process handle + stdlib HTTP
  JSON endpoint (``/predict``, ``/generate`` with chunked streaming,
  ``/models``, ``/healthz``, ``/fleet``, ``/metrics``).
"""

from .batcher import (BATCH_SIZE_BUCKETS, LATENCY_BUCKETS, DeadlineExceeded,
                      DynamicBatcher, Future, InvalidRequest, Overloaded)
from .controller import (AutoscalePolicy, DeviceFleet, FleetController,
                         Observation)
from .decode import (TTFT_BUCKETS, DecodeEngine, GenerateSession,
                     ReplicaKilled)
from .frontend import ServingHandle, ServingHTTPServer
from .kvblocks import (BlockAllocator, KVBlockPool, KVBlocksExhausted,
                       PrefixCache)
from .pool import (QuotaExceeded, Replica, ReplicaPool,
                   RetryBudgetExhausted, lm_pool)
from .registry import (MANIFEST, ModelRegistry, ServedModel, UnknownModel,
                       save_model)

__all__ = ["DynamicBatcher", "Future", "Overloaded", "DeadlineExceeded",
           "InvalidRequest", "LATENCY_BUCKETS", "BATCH_SIZE_BUCKETS",
           "TTFT_BUCKETS", "DecodeEngine", "GenerateSession",
           "ReplicaKilled", "QuotaExceeded", "RetryBudgetExhausted",
           "Replica", "ReplicaPool", "lm_pool",
           "BlockAllocator", "PrefixCache", "KVBlockPool",
           "KVBlocksExhausted",
           "ModelRegistry", "ServedModel", "UnknownModel", "save_model",
           "MANIFEST", "ServingHandle", "ServingHTTPServer",
           "AutoscalePolicy", "DeviceFleet", "FleetController",
           "Observation"]
