"""Serving subsystem — a throughput-oriented model server over the
single-request :class:`~mxnet_tpu.predict.Predictor`.

Three layers (see ``docs/serving.md``):

* :mod:`~mxnet_tpu.serving.batcher` — dynamic micro-batching with
  shape-bucket padding, per-request deadlines, and typed
  :class:`Overloaded` admission control;
* :mod:`~mxnet_tpu.serving.registry` — versioned multi-model registry
  with atomic publish (checksummed manifest-last), atomic reload, and
  per-bucket warm-up compilation at load time;
* :mod:`~mxnet_tpu.serving.frontend` — in-process handle + stdlib HTTP
  JSON endpoint (``/predict``, ``/healthz``, ``/metrics``).
"""

from .batcher import (BATCH_SIZE_BUCKETS, LATENCY_BUCKETS, DeadlineExceeded,
                      DynamicBatcher, Future, InvalidRequest, Overloaded)
from .frontend import ServingHandle, ServingHTTPServer
from .registry import (MANIFEST, ModelRegistry, ServedModel, UnknownModel,
                       save_model)

__all__ = ["DynamicBatcher", "Future", "Overloaded", "DeadlineExceeded",
           "InvalidRequest", "LATENCY_BUCKETS", "BATCH_SIZE_BUCKETS",
           "ModelRegistry", "ServedModel", "UnknownModel", "save_model",
           "MANIFEST", "ServingHandle", "ServingHTTPServer"]
