"""Routed multi-replica serving pool — the millions-of-users layer.

One :class:`~mxnet_tpu.serving.decode.DecodeEngine` (or batcher-backed
model) saturates one device; production traffic needs N of them behind
ONE admission surface.  Following the TensorFlow system-design framing
(serving as a first-class system component, not a deployment
afterthought), :class:`ReplicaPool` owns:

* **placement** — N replicas spread over ``jax.devices()`` (round-robin
  when there are fewer devices than replicas), each built by a caller
  factory and owning its engine/batcher;
* **routing** — weighted least-outstanding-rows: a request goes to the
  healthy replica with the lowest ``outstanding / weight``, accounted
  pool-side so routing never touches an engine lock;
* **load discipline on top of the PR 3 admission control** —
  pool-level ``Overloaded`` past ``max_outstanding``
  (``MXNET_POOL_MAX_OUTSTANDING``), priority-aware shedding (past the
  priority watermark only requests with ``priority >=
  priority_floor`` are admitted), and per-tenant quotas
  (:class:`QuotaExceeded`, shed reason ``quota``);
* **replica fault domains** — a per-replica CIRCUIT BREAKER over the
  step-outcome stream: ``quarantine_after`` consecutive failures OR an
  error rate past ``MXNET_POOL_CIRCUIT_THRESHOLD`` over the rolling
  outcome window opens the circuit (replica quarantined, routing skips
  it, telemetry event), recovery re-warms it through the PR 7 warm-up
  path and — after the ``MXNET_POOL_CIRCUIT_COOLDOWN_MS`` cooldown —
  returns it HALF-OPEN: one in-flight probe at a time until a clean
  step closes the circuit (a failed probe re-opens it instantly);
* **session failover** — an in-flight generation on a failing replica
  is NOT shed: its engine hands the held sessions back
  (:meth:`~mxnet_tpu.serving.decode.DecodeEngine.set_health_hooks`
  ``on_migrate``) and the pool re-admits them on a healthy replica by
  re-prefilling ``prompt + generated-so-far`` — bit-identical
  continuation, greedy and temperature, because sampling keys are
  position-derived (see decode.py).  Failure-driven migration attempts
  are bounded by per-tenant RETRY BUDGETS (``MXNET_POOL_RETRY_BUDGET``
  / the ``retry_budgets`` map); past the budget the session sheds
  typed with reason ``retry_budget``;
* **version swaps** — a pool is a registry servable: build the new
  version off-registry, then
  :meth:`~mxnet_tpu.serving.registry.ModelRegistry.register` pointer-
  flips it in; the OLD pool's in-flight stragglers MIGRATE onto the
  new servable (``close(successor=new)`` / :meth:`adopt`) instead of
  being errored out — bit-identical continuation when the successor
  serves the SAME params (a config/infra swap; position-derived keys
  guarantee identity only for identical weights — with new weights
  the continuation draws from the new version's logits, which is the
  point of the deploy).  Version swaps are free for the session: they
  never touch the retry budget.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..base import MXNetError
from ..compile_cache import _env_float, _env_int
from .batcher import DeadlineExceeded, Overloaded
from .decode import DecodeEngine, ReplicaKilled

__all__ = ["QuotaExceeded", "RetryBudgetExhausted", "Replica",
           "ReplicaPool", "lm_pool", "ACTIVE", "QUARANTINED", "WARMING",
           "RETIRING", "CIRCUIT_CLOSED", "CIRCUIT_OPEN",
           "CIRCUIT_HALF_OPEN"]

_log = logging.getLogger("mxnet_tpu.serving")

ACTIVE = "active"
QUARANTINED = "quarantined"
WARMING = "warming"
#: being drained out of the pool by a controller decision (scale-down /
#: rebalance): unpublished from routing while its sessions migrate
RETIRING = "retiring"

_STATE_GAUGE = {ACTIVE: 0, QUARANTINED: 1, WARMING: 2, RETIRING: 3}

CIRCUIT_CLOSED = "closed"
CIRCUIT_OPEN = "open"
CIRCUIT_HALF_OPEN = "half_open"

_CIRCUIT_GAUGE = {CIRCUIT_CLOSED: 0, CIRCUIT_OPEN: 1,
                  CIRCUIT_HALF_OPEN: 2}


class QuotaExceeded(Overloaded):
    """The tenant's outstanding-request quota is exhausted (HTTP 429);
    other tenants are unaffected — that is the point of quotas."""


class RetryBudgetExhausted(MXNetError):
    """The session's failure-driven migration attempts exceeded its
    tenant's retry budget: shed typed with reason ``retry_budget``
    instead of bouncing between dying replicas forever."""


class Replica:
    """One pool member: the engine plus its health/routing bookkeeping
    (all mutable fields guarded by the POOL lock)."""

    __slots__ = ("rid", "device", "engine", "weight", "state", "failures",
                 "routed", "dead")

    def __init__(self, rid, device, engine, weight):
        self.rid = rid
        self.device = device
        self.engine = engine
        self.weight = float(weight)
        if self.weight <= 0:
            raise MXNetError("replica weight must be > 0")
        self.state = ACTIVE
        self.failures = 0
        self.routed = 0
        #: hard-killed (ReplicaKilled): the engine is permanently gone;
        #: the pool serves on the survivors and the FLEET CONTROLLER —
        #: not the pool — decides whether to replace it
        self.dead = False


class ReplicaPool:
    """N routed replicas behind one ``generate()`` surface.

    Parameters
    ----------
    factory : callable(device, replica_id) -> engine
        Builds one replica; the engine must expose ``submit(prompt,
        ..., on_done=)``, ``resume``, ``pending_rows``, ``describe``,
        ``stop``, ``rewarm``, ``start``, ``close`` and accept health
        hooks via ``set_health_hooks`` (what :class:`DecodeEngine`
        provides — see :func:`lm_pool`).
    n_replicas : int
        Pool size; devices are assigned round-robin from ``devices``
        (default ``jax.devices()``).
    weights : sequence of float, optional
        Per-replica routing weights (default all 1.0): routing picks
        the ACTIVE replica minimizing ``outstanding / weight``.
    quotas : dict, optional
        ``tenant -> max outstanding sessions``; key ``"*"`` is the
        default for unlisted tenants (absent = unlimited).
    max_outstanding : int
        Pool-wide admission bound (``MXNET_POOL_MAX_OUTSTANDING``;
        default: the summed replica capacity).
    priority_floor / priority_watermark :
        Past ``priority_watermark * max_outstanding`` outstanding
        sessions, requests with ``priority < priority_floor`` are shed
        (reason ``priority``) so high-priority traffic keeps flowing
        under pressure.
    quarantine_after : int
        Consecutive step failures before a replica's circuit opens
        (``MXNET_POOL_QUARANTINE_AFTER``, default 3).
    retry_budgets : dict, optional
        ``tenant -> max failure-driven migration attempts per
        session``; key ``"*"`` is the default for unlisted tenants
        (``MXNET_POOL_RETRY_BUDGET``, default 3).  Version-swap
        migrations are free.
    circuit_window / circuit_threshold / circuit_min_events :
        Error-rate breaker: over the last ``circuit_window`` step
        outcomes (``MXNET_POOL_CIRCUIT_WINDOW``, 20), a failure
        fraction >= ``circuit_threshold``
        (``MXNET_POOL_CIRCUIT_THRESHOLD``, 0.5) with at least
        ``circuit_min_events`` outcomes recorded
        (``MXNET_POOL_CIRCUIT_MIN_EVENTS``, 4) opens the circuit even
        without ``quarantine_after`` consecutive failures.
    circuit_cooldown : float, seconds
        Minimum open time before the half-open probe
        (``MXNET_POOL_CIRCUIT_COOLDOWN_MS``, 250ms; re-warm time
        counts toward it).
    """

    def __init__(self, factory, n_replicas=2, devices=None, *, name="lm",
                 version=1, weights=None, quotas=None, max_outstanding=None,
                 priority_floor=5, priority_watermark=0.75,
                 quarantine_after=None, retry_budgets=None,
                 circuit_window=None, circuit_threshold=None,
                 circuit_min_events=None, circuit_cooldown=None):
        import jax

        if n_replicas < 1:
            raise MXNetError("pool needs >= 1 replica")
        self.name = name
        self.version = int(version)
        devices = list(devices) if devices is not None else jax.devices()
        if not devices:
            raise MXNetError("no devices for the replica pool")
        weights = list(weights) if weights is not None \
            else [1.0] * n_replicas
        if len(weights) != n_replicas:
            raise MXNetError("got %d weights for %d replicas"
                             % (len(weights), n_replicas))
        self._lock = threading.Lock()
        self._quotas = dict(quotas or {})
        self._priority_floor = int(priority_floor)
        self._quarantine_after = int(quarantine_after) \
            if quarantine_after is not None \
            else _env_int("MXNET_POOL_QUARANTINE_AFTER", 3)
        self._retry_budgets = dict(retry_budgets or {})
        self._retry_budgets.setdefault(
            "*", _env_int("MXNET_POOL_RETRY_BUDGET", 3))
        self._circuit_window = int(circuit_window) \
            if circuit_window is not None \
            else _env_int("MXNET_POOL_CIRCUIT_WINDOW", 20)
        self._circuit_threshold = float(circuit_threshold) \
            if circuit_threshold is not None \
            else _env_float("MXNET_POOL_CIRCUIT_THRESHOLD", 0.5)
        self._circuit_min_events = int(circuit_min_events) \
            if circuit_min_events is not None \
            else _env_int("MXNET_POOL_CIRCUIT_MIN_EVENTS", 4)
        self._circuit_cooldown = float(circuit_cooldown) \
            if circuit_cooldown is not None \
            else _env_float("MXNET_POOL_CIRCUIT_COOLDOWN_MS", 250) / 1e3
        self._outstanding = {}
        self._tenant_out = {}
        self._total_outstanding = 0
        self._closed = False
        #: fleet-exhausted admission pressure (the controller's typed-
        #: shed lever): while set, the priority floor applies from the
        #: FIRST outstanding request instead of from the watermark
        self._pressure = False
        # circuit-breaker state, all keyed by rid and guarded by the
        # pool lock (the lock-discipline pass pins this — see
        # tests/test_graftlint.py strip-the-lock mutation)
        self._circuit = {}
        self._cwindow = {}       # rid -> deque of step outcomes (bool)
        self._opened_at = {}
        self._migrations_out = {}
        self._migrations_in = {}
        self._failovers = 0
        if any(float(w) <= 0 for w in weights):
            # validate BEFORE building engines: a bad weight must not
            # cost k warmed-and-leaked replicas
            raise MXNetError("replica weights must be > 0, got %r"
                             % (weights,))
        # replica membership is DYNAMIC (the fleet controller scales
        # it): keyed by rid in _replicas, every mutation under the pool
        # lock; the public .replicas property snapshots a rid-ordered
        # list.  The factory and device ring are kept so add_replica
        # can build new members.
        self._factory = factory
        self._devices = devices
        self._next_rid = n_replicas
        self._replicas = {}
        try:
            for i in range(n_replicas):
                dev = devices[i % len(devices)]
                engine = factory(dev, str(i))
                if hasattr(engine, "set_health_hooks"):
                    engine.set_health_hooks(
                        on_error=self._make_error_hook(i),
                        on_ok=self._make_ok_hook(i),
                        on_migrate=self._make_migrate_hook(i))
                self._replicas[i] = Replica(i, dev, engine, weights[i])
                self._outstanding[i] = 0
                self._circuit[i] = CIRCUIT_CLOSED
                self._cwindow[i] = deque(maxlen=self._circuit_window)
                self._opened_at[i] = 0.0
                self._migrations_out[i] = 0
                self._migrations_in[i] = 0
        except Exception:
            # a replica k>0 failing to build (device OOM, ...) must not
            # leak the already-running earlier replicas' worker threads
            # and device-resident caches
            for r in self._replicas.values():
                try:
                    r.engine.close(drain=False)
                except Exception:  # noqa: broad-except — best-effort
                    # cleanup on the failure path
                    pass
            raise
        self.replicas = [self._replicas[k] for k in sorted(self._replicas)]
        env_max = _env_int("MXNET_POOL_MAX_OUTSTANDING", 0)
        # a caller-pinned (or env-pinned) admission bound stays fixed as
        # the pool scales; a capacity-derived one is recomputed on every
        # add/remove so scaling actually moves the admission surface
        self._bound_fixed = max_outstanding is not None or bool(env_max)
        self._watermark_frac = float(priority_watermark)
        self._max_outstanding = int(max_outstanding) \
            if max_outstanding is not None \
            else (env_max or max(self._capacity_locked(), n_replicas))
        # never floor to 0: an idle tiny pool must not shed low-priority
        # traffic before a single request is outstanding
        self._watermark = max(1, int(priority_watermark
                                     * self._max_outstanding))
        for r in self.replicas:
            _telemetry.inc("serving.pool.routed.count", 0,
                           model=name, replica=str(r.rid))
            _telemetry.set_gauge("serving.pool.outstanding", 0,
                                 model=name, replica=str(r.rid))
            _telemetry.set_gauge("serving.pool.replica_state",
                                 _STATE_GAUGE[ACTIVE], model=name,
                                 replica=str(r.rid))
            _telemetry.set_gauge("serving.pool.circuit_state",
                                 _CIRCUIT_GAUGE[CIRCUIT_CLOSED],
                                 model=name, replica=str(r.rid))
            _telemetry.inc("serving.failover.migrations.count", 0,
                           model=name, replica=str(r.rid))
        _telemetry.inc("serving.pool.quarantines.count", 0, model=name)
        _telemetry.inc("serving.failover.count", 0, model=name)
        for reason in ("quota", "priority", "retry_budget", "failover"):
            _telemetry.inc("serving.shed.count", 0, model=name,
                           reason=reason)

    # -- membership --------------------------------------------------------
    def _publish_locked(self):
        """Rebind the public ``replicas`` snapshot (pool lock held).
        ``replicas`` is a rid-ordered IMMUTABLE-by-convention list that
        is REPLACED wholesale on every membership change — readers
        (routing hooks, describe callers, tests) grab the reference
        lock-free and iterate a stable snapshot, exactly the pre-PR-16
        fixed-list read behavior."""
        self.replicas = [self._replicas[k] for k in sorted(self._replicas)]

    def _capacity_locked(self):
        return sum(getattr(r.engine, "slots", 0)
                   + getattr(r.engine, "max_queue", 0)
                   for r in self._replicas.values())

    def _recompute_bounds_locked(self):
        """Re-derive the admission bound + priority watermark after a
        membership change (no-op when the bound was pinned by the
        caller or ``MXNET_POOL_MAX_OUTSTANDING``)."""
        if self._bound_fixed:
            return
        self._max_outstanding = max(self._capacity_locked(),
                                    len(self._replicas), 1)
        self._watermark = max(1, int(self._watermark_frac
                                     * self._max_outstanding))

    def add_replica(self, device=None, weight=1.0):
        """Grow the pool by one replica — the fleet controller's
        scale-up / replace actuator.  The engine is built and WARMED by
        the factory BEFORE the pool publishes it to routing (the PR 7
        warm-up manifests make that warm-up cache loads, not cold
        compiles), so the new replica's first request never pays a
        compile.  Returns the new rid."""
        with self._lock:
            if self._closed:
                raise MXNetError("replica pool %r is closed" % self.name)
            rid = self._next_rid
            self._next_rid += 1
            dev = device if device is not None \
                else self._devices[rid % len(self._devices)]
        engine = self._factory(dev, str(rid))
        if hasattr(engine, "set_health_hooks"):
            engine.set_health_hooks(
                on_error=self._make_error_hook(rid),
                on_ok=self._make_ok_hook(rid),
                on_migrate=self._make_migrate_hook(rid))
        r = Replica(rid, dev, engine, weight)
        with self._lock:
            closed = self._closed
            if not closed:
                self._replicas[rid] = r
                self._outstanding[rid] = 0
                self._circuit[rid] = CIRCUIT_CLOSED
                self._cwindow[rid] = deque(maxlen=self._circuit_window)
                self._opened_at[rid] = 0.0
                self._migrations_out[rid] = 0
                self._migrations_in[rid] = 0
                self._recompute_bounds_locked()
                self._publish_locked()
        if closed:
            # the pool was swapped out while the engine warmed: a
            # replica nobody will ever route to must not leak a worker
            try:
                engine.close(drain=False)
            except Exception:  # noqa: broad-except — best-effort
                # cleanup on the lost-race path
                pass
            raise MXNetError("replica pool %r closed during add_replica"
                             % self.name)
        _telemetry.set_gauge("serving.pool.outstanding", 0,
                             model=self.name, replica=str(rid))
        _telemetry.set_gauge("serving.pool.replica_state",
                             _STATE_GAUGE[ACTIVE], model=self.name,
                             replica=str(rid))
        _telemetry.set_gauge("serving.pool.circuit_state",
                             _CIRCUIT_GAUGE[CIRCUIT_CLOSED],
                             model=self.name, replica=str(rid))
        _telemetry.event("serving.pool.replica_add", model=self.name,
                         replica=str(rid), device=str(dev))
        _log.info("pool %r: replica %d added on %s (warmed before "
                  "routing)", self.name, rid, dev)
        return rid

    def remove_replica(self, rid, migrate=True):
        """Shrink the pool by one replica — the scale-down / rebalance
        actuator.  The replica is unpublished from routing (RETIRING),
        its engine stopped with the live sessions HANDED OFF, and each
        handed session re-admitted on a survivor through the failover
        transport (``resume()``: re-prefill prompt + generated-so-far —
        bit-identical continuation) WITHOUT charging the tenant's retry
        budget: a controller decision is not a replica failure.
        Returns True when no session was lost (migrated sessions are
        not losses; shed sessions carry a typed error)."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                raise MXNetError("pool %r has no replica %r"
                                 % (self.name, rid))
            if r.state == RETIRING:
                return True  # a concurrent remove already owns it
            was_dead = r.dead
            r.state = RETIRING
        _telemetry.set_gauge("serving.pool.replica_state",
                             _STATE_GAUGE[RETIRING], model=self.name,
                             replica=str(rid))
        clean = True
        orphans = []
        try:
            r.engine.stop(drain=False, hand_off=orphans.extend)
        except Exception:  # noqa: broad-except — a dead engine's stop
            # must not block the membership change
            clean = False
            _log.warning("pool %r: stop of replica %d failed during "
                         "removal", self.name, rid, exc_info=True)
        if orphans:
            if migrate:
                self._migrate_sessions(
                    rid, orphans,
                    MXNetError("replica %d retired by the fleet "
                               "controller" % rid),
                    charge_budget=False, reason="rebalance")
            else:
                for sess in orphans:
                    clean = False
                    self._shed_session(sess, "drain", MXNetError(
                        "replica %d removed from pool %r before this "
                        "session finished" % (rid, self.name)))
        try:
            r.engine.close(drain=False)
        except Exception:  # noqa: broad-except — closing one dead
            # replica must not block the membership change
            if not was_dead:
                clean = False
            _log.warning("pool %r: close of replica %d failed during "
                         "removal", self.name, rid, exc_info=True)
        with self._lock:
            self._replicas.pop(rid, None)
            self._outstanding.pop(rid, None)
            self._circuit.pop(rid, None)
            self._cwindow.pop(rid, None)
            self._opened_at.pop(rid, None)
            self._migrations_out.pop(rid, None)
            self._migrations_in.pop(rid, None)
            self._recompute_bounds_locked()
            self._publish_locked()
        _telemetry.set_gauge("serving.pool.outstanding", 0,
                             model=self.name, replica=str(rid))
        _telemetry.event("serving.pool.replica_remove", model=self.name,
                         replica=str(rid), migrated=len(orphans),
                         clean=clean)
        _log.info("pool %r: replica %d removed (%d live session(s) "
                  "migrated)", self.name, rid,
                  len(orphans) if migrate else 0)
        return clean

    def set_shed_pressure(self, on):
        """Fleet-exhausted admission pressure — the controller's
        priority-shedding lever (tentpole (d)): while on, requests
        under the priority floor shed typed (reason ``priority``) from
        the FIRST outstanding request instead of from the watermark.
        In-flight generations are never touched — this is admission
        control only.  Returns the previous setting."""
        on = bool(on)
        with self._lock:
            prev, self._pressure = self._pressure, on
        if prev != on:
            _telemetry.set_gauge("serving.pool.shed_pressure", int(on),
                                 model=self.name)
            _telemetry.event("serving.pool.shed_pressure",
                             model=self.name, on=on)
            _log.warning("pool %r: shed pressure %s", self.name,
                         "ON (priority floor applies from the first "
                         "request)" if on else "off")
        return prev

    def admission_state(self):
        """``(outstanding, max_outstanding, shed_pressure)`` — the
        controller's cheap per-tick load read (no engine locks)."""
        with self._lock:
            return (self._total_outstanding, self._max_outstanding,
                    self._pressure)

    def _make_error_hook(self, rid):
        return lambda exc: self._note_step_error(rid, exc)

    def _make_ok_hook(self, rid):
        return lambda: self._note_step_ok(rid)

    def _make_migrate_hook(self, rid):
        return lambda sessions, exc: self._migrate_sessions(
            rid, sessions, exc)

    # -- routing -----------------------------------------------------------
    def _pick_locked(self):
        """Weighted least-outstanding choice over routable replicas
        (pool lock held).  A HALF-OPEN replica is routable but admits
        ONE in-flight probe at a time — the breaker's probe, carried by
        real traffic — and NEVER outbids a CLOSED-circuit replica just
        by being idle: recovering capacity is unproven, so under
        degradation the proven replica is preferred even at a higher
        outstanding count.  The probe flows only when every closed-
        circuit replica is already slot-saturated (real pressure) or
        none is routable at all — prompt enough to close the breaker,
        never the first choice.  Returns None when nothing is
        routable."""
        closed, probes = [], []
        for r in self._replicas.values():  # lint: ok[lock-discipline] call-with-pool-lock-held helper; every call site (generate/adopt/_migrate_sessions) holds self._lock, the thread path included
            if r.state != ACTIVE:
                continue
            circuit = self._circuit[r.rid]  # lint: ok[lock-discipline] call-with-pool-lock-held helper (see above)
            busy = self._outstanding[r.rid]  # lint: ok[lock-discipline] call-with-pool-lock-held helper (see above)
            if circuit == CIRCUIT_HALF_OPEN:
                if busy >= 1:
                    continue  # the probe budget: one in flight
                probes.append(r)
            else:
                closed.append(r)
        key = lambda x: self._outstanding[x.rid] / x.weight  # noqa: E731  # lint: ok[lock-discipline] call-with-pool-lock-held helper (see above)
        if closed:
            if probes and all(
                    self._outstanding[r.rid]  # lint: ok[lock-discipline] call-with-pool-lock-held helper (see above)
                    >= max(1, getattr(r.engine, "slots", 1))
                    for r in closed):
                return min(probes, key=key)
            return min(closed, key=key)
        if probes:
            return min(probes, key=key)
        return None

    def generate(self, prompt, *, max_new_tokens=16, temperature=0.0,
                 deadline_ms=None, on_token=None, tenant=None, priority=5,
                 seed=None, on_event=None):
        """Admit + route one generation request; returns the replica
        engine's :class:`~mxnet_tpu.serving.decode.GenerateSession`.

        Shedding order (all typed, all counted under
        ``serving.shed.count{model=,reason=}``): pool ``Overloaded``
        past ``max_outstanding``; ``priority`` past the watermark for
        requests under the floor; ``quota`` for tenants at their bound;
        then the chosen replica's own engine admission applies.
        ``on_event`` (optional ``callable(kind, info)``) receives a
        ``"failover"`` notification at every migration boundary — the
        HTTP frontend turns it into the stream's failover line."""
        tenant_key = tenant if tenant is not None else "*"
        with self._lock:
            if self._closed:
                raise MXNetError("replica pool %r is closed" % self.name)
            if self._total_outstanding >= self._max_outstanding:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="overload")
                self._trace_shed("overload")
                raise Overloaded(
                    "pool %r overloaded: %d outstanding >= bound %d"
                    % (self.name, self._total_outstanding,
                       self._max_outstanding))
            if (self._pressure
                    or self._total_outstanding >= self._watermark) \
                    and int(priority) < self._priority_floor:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="priority")
                self._trace_shed("priority")
                raise Overloaded(
                    "pool %r past its priority watermark (%d/%d)%s: "
                    "priority %d < floor %d shed"
                    % (self.name, self._total_outstanding,
                       self._watermark,
                       " under fleet shed pressure" if self._pressure
                       else "", priority, self._priority_floor))
            quota = self._quotas.get(tenant_key, self._quotas.get("*"))
            if quota is not None \
                    and self._tenant_out.get(tenant_key, 0) >= int(quota):
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="quota")
                self._trace_shed("quota")
                raise QuotaExceeded(
                    "tenant %r at its quota of %d outstanding requests"
                    % (tenant_key, int(quota)))
            r = self._pick_locked()
            if r is None:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="overload")
                self._trace_shed("no_replica")
                raise Overloaded("pool %r has no healthy replicas "
                                 "(all quarantined/warming)" % self.name)
            self._outstanding[r.rid] += 1
            self._tenant_out[tenant_key] = \
                self._tenant_out.get(tenant_key, 0) + 1
            self._total_outstanding += 1
            r.routed += 1
            _telemetry.inc("serving.pool.routed.count", model=self.name,
                           replica=str(r.rid))
            _telemetry.set_gauge("serving.pool.outstanding",
                                 self._outstanding[r.rid],
                                 model=self.name, replica=str(r.rid))
        try:
            sess = r.engine.submit(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, deadline_ms=deadline_ms,
                on_token=on_token, seed=seed, tenant=tenant_key,
                on_event=on_event,
                on_done=self._make_done_hook(r.rid, tenant_key))
        except Exception:
            self._settle(r.rid, tenant_key)
            raise
        return sess

    def _make_done_hook(self, rid, tenant_key):
        return lambda _sess: self._settle(rid, tenant_key)

    def _settle(self, rid, tenant_key):
        with self._lock:
            # the rid may have been removed by a controller scale-down
            # while this session was finishing: tenant/total accounting
            # still settles, the per-replica row is simply gone
            out = None
            if rid in self._outstanding:
                self._outstanding[rid] = \
                    max(0, self._outstanding[rid] - 1)
                out = self._outstanding[rid]
            self._tenant_out[tenant_key] = \
                max(0, self._tenant_out.get(tenant_key, 0) - 1)
            self._total_outstanding = max(0, self._total_outstanding - 1)
        if out is not None:
            _telemetry.set_gauge("serving.pool.outstanding", out,
                                 model=self.name, replica=str(rid))

    # -- replica health / circuit breaker ----------------------------------
    def _failure_rate_locked(self, rid):
        window = self._cwindow[rid]
        if not window:
            return 0.0
        return sum(1 for ok in window if not ok) / float(len(window))

    def _note_step_error(self, rid, exc):
        killed = isinstance(exc, ReplicaKilled)
        r = self._replicas.get(rid)
        if r is None:
            return  # removed by a controller scale-down mid-flight
        with self._lock:
            r.failures += 1
            self._cwindow[rid].append(False)
            rate = self._failure_rate_locked(rid)
            opened = r.state == ACTIVE and (
                killed
                or self._circuit[rid] == CIRCUIT_HALF_OPEN
                or r.failures >= self._quarantine_after
                or (len(self._cwindow[rid]) >= self._circuit_min_events
                    and rate >= self._circuit_threshold))
            if opened:
                r.state = QUARANTINED
                self._circuit[rid] = CIRCUIT_OPEN
                self._opened_at[rid] = time.monotonic()
            failures = r.failures
        if not opened:
            return
        _telemetry.inc("serving.pool.quarantines.count", model=self.name)
        _telemetry.set_gauge("serving.pool.replica_state",
                             _STATE_GAUGE[QUARANTINED],
                             model=self.name, replica=str(rid))
        _telemetry.set_gauge("serving.pool.circuit_state",
                             _CIRCUIT_GAUGE[CIRCUIT_OPEN],
                             model=self.name, replica=str(rid))
        _telemetry.event("serving.pool.quarantine", model=self.name,
                         replica=str(rid), failures=failures,
                         error=str(exc))
        _telemetry.event("serving.pool.circuit_open", model=self.name,
                         replica=str(rid),
                         failure_rate=round(rate, 3), killed=killed)
        _log.warning("pool %r: replica %d circuit OPEN after %d "
                     "consecutive failures / %.0f%% window error rate "
                     "(%s)%s", self.name, rid, failures, rate * 100, exc,
                     "; replica hard-killed, staying down" if killed
                     else "; recovering in the background")
        threading.Thread(target=self._recover, args=(rid, killed, exc),
                         name="pool-recover-%s-%d" % (self.name, rid),
                         daemon=True).start()

    def _note_step_ok(self, rid):
        r = self._replicas.get(rid)
        if r is None:
            return  # removed by a controller scale-down mid-flight
        with self._lock:
            r.failures = 0
            self._cwindow[rid].append(True)
            closed = self._circuit[rid] == CIRCUIT_HALF_OPEN \
                and r.state == ACTIVE
            if closed:
                self._circuit[rid] = CIRCUIT_CLOSED
        if closed:
            _telemetry.set_gauge("serving.pool.circuit_state",
                                 _CIRCUIT_GAUGE[CIRCUIT_CLOSED],
                                 model=self.name, replica=str(rid))
            _telemetry.event("serving.pool.circuit_close",
                             model=self.name, replica=str(rid))
            _log.info("pool %r: replica %d half-open probe succeeded; "
                      "circuit CLOSED", self.name, rid)

    def _recover(self, rid, killed, exc):
        """Background circuit recovery: take over everything the
        opened replica still holds (queued AND slot sessions migrate,
        they are not shed), then — unless the replica was hard-killed —
        re-warm it, sit out the cooldown, and return it HALF-OPEN."""
        with self._lock:
            if self._closed:
                # the pool was swapped out while recovery was pending;
                # the engine-level closed guard catches the narrower
                # race after this check
                return
            r = self._replicas.get(rid)
        if r is None:
            return  # removed by a controller scale-down mid-recovery
        orphans = []
        try:
            r.engine.stop(drain=False, hand_off=orphans.extend)
        except Exception:  # noqa: broad-except — a dead engine's stop
            # must not kill the recovery thread before migration
            _log.warning("pool %r: stop of replica %d failed during "
                         "recovery", self.name, rid, exc_info=True)
        if orphans:
            self._migrate_sessions(rid, orphans, exc)
        if killed:
            with self._lock:
                r.dead = True
            _telemetry.event("serving.pool.replica_dead",
                             model=self.name, replica=str(rid),
                             error=str(exc))
            _log.error("pool %r: replica %d is dead (hard kill); "
                       "serving continues on the survivors — replace/"
                       "quarantine is the fleet controller's call",
                       self.name, rid)
            return
        with self._lock:
            r.state = WARMING
        _telemetry.set_gauge("serving.pool.replica_state",
                             _STATE_GAUGE[WARMING], model=self.name,
                             replica=str(rid))
        try:
            r.engine.rewarm()
            r.engine.start()
        except Exception as e:  # noqa: broad-except — a failed re-warm
            # must leave the replica quarantined (and the pool serving on
            # the others), never kill the recovery thread with the
            # replica stuck WARMING
            with self._lock:
                r.state = QUARANTINED
            _telemetry.set_gauge("serving.pool.replica_state",
                                 _STATE_GAUGE[QUARANTINED],
                                 model=self.name, replica=str(rid))
            _telemetry.event("serving.pool.rewarm_failed",
                             model=self.name, replica=str(rid),
                             error=str(e))
            _log.error("pool %r: re-warm of replica %d failed: %s",
                       self.name, rid, e)
            return
        # re-warm time counts toward the cooldown; sit out any rest so
        # a fast re-warm cannot flap the breaker
        with self._lock:
            opened_at = self._opened_at[rid]
        remaining = opened_at + self._circuit_cooldown - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        with self._lock:
            r.state = ACTIVE
            r.failures = 0
            self._cwindow[rid].clear()
            self._circuit[rid] = CIRCUIT_HALF_OPEN
        _telemetry.set_gauge("serving.pool.replica_state",
                             _STATE_GAUGE[ACTIVE], model=self.name,
                             replica=str(rid))
        _telemetry.set_gauge("serving.pool.circuit_state",
                             _CIRCUIT_GAUGE[CIRCUIT_HALF_OPEN],
                             model=self.name, replica=str(rid))
        _telemetry.event("serving.pool.rewarmed", model=self.name,
                         replica=str(rid))
        _telemetry.event("serving.pool.circuit_half_open",
                         model=self.name, replica=str(rid))
        _log.info("pool %r: replica %d re-warmed; circuit HALF-OPEN "
                  "(one probe at a time)", self.name, rid)

    # -- session failover ---------------------------------------------------
    def _retry_budget(self, tenant_key):
        budget = self._retry_budgets.get(
            tenant_key, self._retry_budgets.get("*", 3))
        return int(budget)

    def _shed_session(self, sess, reason, err):
        _telemetry.inc("serving.shed.count", model=self.name,
                       reason=reason)
        sess.trace.end("shed", reason=reason)
        sess._resolve(error=err)

    def _trace_shed(self, reason):
        # pool-level sheds happen BEFORE a session (and its root span)
        # exists: mint a zero-length shed span so rejected requests
        # still show up in the caller's trace
        _tracing.start_span("serving.generate", stack=False,
                            model=self.name).end("shed", reason=reason)

    def _fire_failover_event(self, sess, info):
        if sess.trace:
            info.setdefault("trace_id", sess.trace.trace_id)
        cb = sess.on_event
        if cb is None:
            return
        try:
            cb("failover", info)
        except Exception:  # noqa: broad-except — a client callback must
            # never kill the migration path
            _log.warning("pool %r: on_event callback failed", self.name,
                         exc_info=True)

    def _migrate_sessions(self, rid, sessions, exc, charge_budget=True,
                          reason="failover"):
        """Failure-driven migration (the engines' ``on_migrate`` hook
        and the recovery takeover): re-admit each session on a healthy
        replica — its accounting moves with it — or shed typed when it
        is cancelled/expired, over its retry budget, or nothing is
        routable.  Every session is resolved-or-readmitted; none is
        ever silently dropped.  ``charge_budget=False`` is the
        controller-drain variant (scale-down / rebalance): like a
        version swap, a planned migration is free for the session —
        the retry budget guards against bouncing between DYING
        replicas, not against operator decisions."""
        tenant_of = lambda s: s.tenant if s.tenant is not None else "*"  # noqa: E731
        for sess in sessions:
            if sess.finished():
                continue
            if sess.cancelled():
                self._shed_session(sess, "abandoned", MXNetError(
                    "session abandoned by the client during failover"))
                continue
            if sess.deadline is not None \
                    and time.monotonic() > sess.deadline:
                self._shed_session(sess, "deadline", DeadlineExceeded(
                    "session deadline expired during failover"))
                continue
            tenant_key = tenant_of(sess)
            if charge_budget:
                sess.migrations += 1
                budget = self._retry_budget(tenant_key)
                if sess.migrations > budget:
                    self._shed_session(sess, "retry_budget",
                                       RetryBudgetExhausted(
                        "session exceeded tenant %r retry budget of %d "
                        "migration attempts (reason=retry_budget); last "
                        "replica error: %s" % (tenant_key, budget, exc)))
                    continue
            t0 = time.monotonic()
            with self._lock:
                target = None if self._closed else self._pick_locked()
                if target is not None:
                    # the accounting moves with the session: the source
                    # replica sheds one outstanding row, the target
                    # gains it (tenant/total are unchanged)
                    self._outstanding[rid] = \
                        max(0, self._outstanding[rid] - 1)
                    self._outstanding[target.rid] += 1
                    target.routed += 1
                    self._migrations_out[rid] += 1
                    self._migrations_in[target.rid] += 1
                    self._failovers += 1
                    out_src = self._outstanding[rid]
                    out_dst = self._outstanding[target.rid]
            if target is None:
                self._shed_session(sess, reason, MXNetError(
                    "no healthy replica to migrate this session to; "
                    "replica error: %s" % (exc,)))
                continue
            _telemetry.set_gauge("serving.pool.outstanding", out_src,
                                 model=self.name, replica=str(rid))
            _telemetry.set_gauge("serving.pool.outstanding", out_dst,
                                 model=self.name, replica=str(target.rid))
            sess._on_done = self._make_done_hook(target.rid, tenant_key)
            # the hop itself is a span under the session root: the
            # assembled trace shows replica A's admit, the failover
            # hop, then replica B's re-admit — one rooted tree
            fsp = _tracing.start_span(
                "serving.failover", parent=sess.trace, stack=False,
                from_replica=str(rid), to_replica=str(target.rid),
                attempt=sess.migrations, reason=reason)
            # the stream's failover line goes out BEFORE resume(): the
            # target worker can emit the first resumed token the moment
            # the session is enqueued, and the event must precede it
            self._fire_failover_event(sess, {
                "from_replica": str(rid), "to_replica": str(target.rid),
                "attempt": sess.migrations})
            sess.migrate_t0 = t0
            try:
                target.engine.resume(sess)
            except Exception as e:  # noqa: broad-except — a refused
                # resume (transcript outgrew the buckets, target closing
                # under a racing swap) sheds typed, never drops
                sess.migrate_t0 = None
                fsp.end("error", error=type(e).__name__)
                self._shed_session(sess, reason, MXNetError(
                    "failover re-admission on replica %d failed: %s"
                    % (target.rid, e)))
                continue
            fsp.end("migrated")
            _telemetry.inc("serving.failover.count", model=self.name)
            _telemetry.inc("serving.failover.migrations.count",
                           model=self.name, replica=str(rid))
            _telemetry.event("serving.failover.migrate",
                             model=self.name, src=str(rid),
                             dst=str(target.rid), reason=reason,
                             attempt=sess.migrations,
                             tokens_generated=len(sess.tokens),
                             **({"trace_id": sess.trace.trace_id}
                                if sess.trace else {}))

    def adopt(self, sess):
        """Admit an in-flight session migrated from OUTSIDE this pool —
        a version swap's straggler (``old.close(successor=new)``):
        fresh accounting, no admission bounds (it was already admitted
        once), no retry-budget charge (a version swap is not a
        failure).  Raises when nothing is routable; the caller sheds
        typed."""
        tenant_key = sess.tenant if sess.tenant is not None else "*"
        with self._lock:
            if self._closed:
                raise MXNetError("replica pool %r is closed" % self.name)
            target = self._pick_locked()
            if target is None:
                raise Overloaded("pool %r has no healthy replicas to "
                                 "adopt the migrated session"
                                 % self.name)
            self._outstanding[target.rid] += 1
            self._tenant_out[tenant_key] = \
                self._tenant_out.get(tenant_key, 0) + 1
            self._total_outstanding += 1
            target.routed += 1
            self._migrations_in[target.rid] += 1
            self._failovers += 1
        sess._on_done = self._make_done_hook(target.rid, tenant_key)
        fsp = _tracing.start_span(
            "serving.failover", parent=sess.trace, stack=False,
            to_replica=str(target.rid), version_swap=True)
        # event before resume(), as in _migrate_sessions: the stream's
        # failover line must precede the first successor-side token
        self._fire_failover_event(sess, {
            "to_replica": str(target.rid), "version_swap": True})
        try:
            target.engine.resume(sess)
        except Exception as e:
            fsp.end("error", error=type(e).__name__)
            self._settle(target.rid, tenant_key)
            raise
        fsp.end("migrated")
        _telemetry.inc("serving.failover.count", model=self.name)
        _telemetry.event("serving.failover.adopt", model=self.name,
                         dst=str(target.rid),
                         tokens_generated=len(sess.tokens),
                         **({"trace_id": sess.trace.trace_id}
                            if sess.trace else {}))
        return sess

    # -- registry servable surface ----------------------------------------
    def pending_rows(self):
        """Queued + active sequences across every replica — the
        graceful-drain quiescence probe."""
        return sum(r.engine.pending_rows() for r in self.replicas)

    def outstanding(self):
        with self._lock:
            return self._total_outstanding

    def describe(self):
        with self._lock:
            reps = [dict(r.engine.describe(), state=r.state,
                         circuit=self._circuit[r.rid],
                         failure_rate=round(
                             self._failure_rate_locked(r.rid), 3),
                         failures=r.failures, routed=r.routed,
                         dead=r.dead,
                         migrations_out=self._migrations_out[r.rid],
                         migrations_in=self._migrations_in[r.rid],
                         outstanding=self._outstanding[r.rid],
                         weight=r.weight)
                    for k in sorted(self._replicas)
                    for r in (self._replicas[k],)]
            total = self._total_outstanding
            tenants = dict(self._tenant_out)
            failovers = self._failovers
            pressure = self._pressure
            max_out = self._max_outstanding
        # pool-level KV storage rollup (per-replica cards keep the
        # detail): /healthz reads occupancy from here without walking
        # replicas
        kv_cards = [r.get("kv") for r in reps if r.get("kv")]
        paged = [k for k in kv_cards if k.get("layout") == "paged"]
        if paged:
            kv = {"layout": "paged",
                  "block_size": paged[0]["block_size"],
                  "num_blocks": sum(k["num_blocks"] for k in paged),
                  "blocks_used": sum(k["blocks_used"] for k in paged),
                  "blocks_free": sum(k["blocks_free"] for k in paged),
                  "prefix_hits": sum(k["prefix_hits"] for k in paged),
                  "prefix_tokens_reused": sum(k["prefix_tokens_reused"]
                                              for k in paged),
                  "cow_copies": sum(k["cow_copies"] for k in paged),
                  "hbm_bytes": sum(k["hbm_bytes"] for k in paged)}
        elif kv_cards:
            kv = {"layout": "dense",
                  "hbm_bytes": sum(k["hbm_bytes"] for k in kv_cards)}
        else:
            kv = None
        return {"name": self.name, "version": self.version,
                "kind": "generate", "replicas": reps, "kv": kv,
                "outstanding": total,
                "max_outstanding": max_out,
                "priority_floor": self._priority_floor,
                "shed_pressure": pressure,
                "quotas": dict(self._quotas),
                "retry_budgets": dict(self._retry_budgets),
                "failovers": failovers,
                "tenants_outstanding": tenants}

    def close(self, drain=True, successor=None):
        """Drain (by default) and permanently stop every replica — what
        the registry calls on the OLD pool after a pointer-flip swap.
        With ``successor`` (the newly registered servable), in-flight
        stragglers are NOT errored: each one migrates onto the
        successor (``adopt``/``resume``) and finishes there —
        bit-identical to an uninterrupted run when the successor
        serves the same params (see the class docstring for the
        new-weights case).  Returns True when no session was lost
        (migrated sessions are not losses; shed sessions carry a typed
        error, they are never silently dropped)."""
        with self._lock:
            self._closed = True
        clean = True
        adopt = None
        if successor is not None:
            adopt = getattr(successor, "adopt", None) \
                or getattr(successor, "resume", None)
        for r in self.replicas:
            if adopt is not None:
                orphans = []
                try:
                    r.engine.stop(drain=False, hand_off=orphans.extend)
                except Exception:  # noqa: broad-except — one dead
                    # replica must not block the swap
                    clean = False
                    _log.warning("pool %r: stop of replica %d failed "
                                 "during version swap", self.name, r.rid,
                                 exc_info=True)
                for sess in orphans:
                    if sess.finished():
                        continue
                    if sess.cancelled():
                        self._shed_session(sess, "abandoned", MXNetError(
                            "session abandoned by the client during a "
                            "version swap"))
                        continue
                    # release THIS pool's accounting; the successor
                    # runs its own books from here on
                    tenant_key = sess.tenant if sess.tenant is not None \
                        else "*"
                    self._settle(r.rid, tenant_key)
                    sess._on_done = None
                    try:
                        adopt(sess)
                    except Exception as e:  # noqa: broad-except — an
                        # unadoptable straggler sheds typed, not lost
                        clean = False
                        self._shed_session(sess, "failover", MXNetError(
                            "version-swap migration failed: %s" % (e,)))
                        continue
                    _telemetry.event("serving.failover.version_swap",
                                     model=self.name, src=str(r.rid),
                                     tokens_generated=len(sess.tokens),
                                     **({"trace_id": sess.trace.trace_id}
                                        if sess.trace else {}))
            try:
                if r.engine.close(drain=drain and adopt is None) is False:
                    clean = False
            except Exception:  # noqa: broad-except — closing one dead
                # replica must not leak the others
                clean = False
                _log.warning("pool %r: close of replica %d failed",
                             self.name, r.rid, exc_info=True)
        return clean


def lm_pool(cfg, params, n_replicas=2, devices=None, *, name="lm",
            version=1, engine_opts=None, **pool_opts):
    """Build a :class:`ReplicaPool` of
    :class:`~mxnet_tpu.serving.decode.DecodeEngine` replicas over a
    :mod:`~mxnet_tpu.models.transformer_lm` — the standard LM-serving
    stack (each replica gets the params committed to ITS device)."""
    opts = dict(engine_opts or {})

    def factory(device, replica_id):
        return DecodeEngine(cfg, params, device=device, name=name,
                            replica=replica_id, autostart=True, **opts)

    return ReplicaPool(factory, n_replicas=n_replicas, devices=devices,
                       name=name, version=version, **pool_opts)
