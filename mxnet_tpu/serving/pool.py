"""Routed multi-replica serving pool — the millions-of-users layer.

One :class:`~mxnet_tpu.serving.decode.DecodeEngine` (or batcher-backed
model) saturates one device; production traffic needs N of them behind
ONE admission surface.  Following the TensorFlow system-design framing
(serving as a first-class system component, not a deployment
afterthought), :class:`ReplicaPool` owns:

* **placement** — N replicas spread over ``jax.devices()`` (round-robin
  when there are fewer devices than replicas), each built by a caller
  factory and owning its engine/batcher;
* **routing** — weighted least-outstanding-rows: a request goes to the
  healthy replica with the lowest ``outstanding / weight``, accounted
  pool-side so routing never touches an engine lock;
* **load discipline on top of the PR 3 admission control** —
  pool-level ``Overloaded`` past ``max_outstanding``
  (``MXNET_POOL_MAX_OUTSTANDING``), priority-aware shedding (past the
  priority watermark only requests with ``priority >=
  priority_floor`` are admitted), and per-tenant quotas
  (:class:`QuotaExceeded`, shed reason ``quota``);
* **replica health** — ``quarantine_after`` consecutive dispatch
  failures (``MXNET_POOL_QUARANTINE_AFTER``) quarantines the replica
  (telemetry event, routing skips it) and a background thread re-warms
  it through the PR 7 warm-up path (persistent-cache loads, zero cold
  compiles on a healthy host) before flipping it back to ACTIVE;
* **version swaps** — a pool is a registry servable: build the new
  version off-registry, then
  :meth:`~mxnet_tpu.serving.registry.ModelRegistry.register` pointer-
  flips it in and drains the old one — no request ever sees a
  half-swapped pool.
"""

from __future__ import annotations

import logging
import threading

from .. import telemetry as _telemetry
from ..base import MXNetError
from ..compile_cache import _env_int
from .batcher import Overloaded
from .decode import DecodeEngine

__all__ = ["QuotaExceeded", "Replica", "ReplicaPool", "lm_pool",
           "ACTIVE", "QUARANTINED", "WARMING"]

_log = logging.getLogger("mxnet_tpu.serving")

ACTIVE = "active"
QUARANTINED = "quarantined"
WARMING = "warming"

_STATE_GAUGE = {ACTIVE: 0, QUARANTINED: 1, WARMING: 2}


class QuotaExceeded(Overloaded):
    """The tenant's outstanding-request quota is exhausted (HTTP 429);
    other tenants are unaffected — that is the point of quotas."""




class Replica:
    """One pool member: the engine plus its health/routing bookkeeping
    (all mutable fields guarded by the POOL lock)."""

    __slots__ = ("rid", "device", "engine", "weight", "state", "failures",
                 "routed")

    def __init__(self, rid, device, engine, weight):
        self.rid = rid
        self.device = device
        self.engine = engine
        self.weight = float(weight)
        if self.weight <= 0:
            raise MXNetError("replica weight must be > 0")
        self.state = ACTIVE
        self.failures = 0
        self.routed = 0


class ReplicaPool:
    """N routed replicas behind one ``generate()`` surface.

    Parameters
    ----------
    factory : callable(device, replica_id) -> engine
        Builds one replica; the engine must expose ``submit(prompt,
        ..., on_done=)``, ``pending_rows``, ``describe``, ``stop``,
        ``rewarm``, ``start``, ``close`` and accept health hooks via
        ``set_health_hooks`` (what :class:`DecodeEngine` provides —
        see :func:`lm_pool`).
    n_replicas : int
        Pool size; devices are assigned round-robin from ``devices``
        (default ``jax.devices()``).
    weights : sequence of float, optional
        Per-replica routing weights (default all 1.0): routing picks
        the ACTIVE replica minimizing ``outstanding / weight``.
    quotas : dict, optional
        ``tenant -> max outstanding sessions``; key ``"*"`` is the
        default for unlisted tenants (absent = unlimited).
    max_outstanding : int
        Pool-wide admission bound (``MXNET_POOL_MAX_OUTSTANDING``;
        default: the summed replica capacity).
    priority_floor / priority_watermark :
        Past ``priority_watermark * max_outstanding`` outstanding
        sessions, requests with ``priority < priority_floor`` are shed
        (reason ``priority``) so high-priority traffic keeps flowing
        under pressure.
    quarantine_after : int
        Consecutive step failures before a replica is quarantined
        (``MXNET_POOL_QUARANTINE_AFTER``, default 3).
    """

    def __init__(self, factory, n_replicas=2, devices=None, *, name="lm",
                 version=1, weights=None, quotas=None, max_outstanding=None,
                 priority_floor=5, priority_watermark=0.75,
                 quarantine_after=None):
        import jax

        if n_replicas < 1:
            raise MXNetError("pool needs >= 1 replica")
        self.name = name
        self.version = int(version)
        devices = list(devices) if devices is not None else jax.devices()
        if not devices:
            raise MXNetError("no devices for the replica pool")
        weights = list(weights) if weights is not None \
            else [1.0] * n_replicas
        if len(weights) != n_replicas:
            raise MXNetError("got %d weights for %d replicas"
                             % (len(weights), n_replicas))
        self._lock = threading.Lock()
        self._quotas = dict(quotas or {})
        self._priority_floor = int(priority_floor)
        self._quarantine_after = int(quarantine_after) \
            if quarantine_after is not None \
            else _env_int("MXNET_POOL_QUARANTINE_AFTER", 3)
        self._outstanding = {}
        self._tenant_out = {}
        self._total_outstanding = 0
        self._closed = False
        if any(float(w) <= 0 for w in weights):
            # validate BEFORE building engines: a bad weight must not
            # cost k warmed-and-leaked replicas
            raise MXNetError("replica weights must be > 0, got %r"
                             % (weights,))
        # replicas list is immutable after init (only their fields
        # mutate, under the pool lock)
        self.replicas = []
        try:
            for i in range(n_replicas):
                dev = devices[i % len(devices)]
                engine = factory(dev, str(i))
                if hasattr(engine, "set_health_hooks"):
                    engine.set_health_hooks(
                        on_error=self._make_error_hook(i),
                        on_ok=self._make_ok_hook(i))
                self.replicas.append(Replica(i, dev, engine, weights[i]))
                self._outstanding[i] = 0
        except Exception:
            # a replica k>0 failing to build (device OOM, ...) must not
            # leak the already-running earlier replicas' worker threads
            # and device-resident caches
            for r in self.replicas:
                try:
                    r.engine.close(drain=False)
                except Exception:  # noqa: broad-except — best-effort
                    # cleanup on the failure path
                    pass
            raise
        cap = sum(getattr(r.engine, "slots", 0)
                  + getattr(r.engine, "max_queue", 0)
                  for r in self.replicas)
        env_max = _env_int("MXNET_POOL_MAX_OUTSTANDING", 0)
        self._max_outstanding = int(max_outstanding) \
            if max_outstanding is not None \
            else (env_max or max(cap, n_replicas))
        # never floor to 0: an idle tiny pool must not shed low-priority
        # traffic before a single request is outstanding
        self._watermark = max(1, int(priority_watermark
                                     * self._max_outstanding))
        for r in self.replicas:
            _telemetry.inc("serving.pool.routed.count", 0,
                           model=name, replica=str(r.rid))
            _telemetry.set_gauge("serving.pool.outstanding", 0,
                                 model=name, replica=str(r.rid))
            _telemetry.set_gauge("serving.pool.replica_state",
                                 _STATE_GAUGE[ACTIVE], model=name,
                                 replica=str(r.rid))
        _telemetry.inc("serving.pool.quarantines.count", 0, model=name)
        for reason in ("quota", "priority"):
            _telemetry.inc("serving.shed.count", 0, model=name,
                           reason=reason)

    def _make_error_hook(self, rid):
        return lambda exc: self._note_step_error(rid, exc)

    def _make_ok_hook(self, rid):
        return lambda: self._note_step_ok(rid)

    # -- routing -----------------------------------------------------------
    def generate(self, prompt, *, max_new_tokens=16, temperature=0.0,
                 deadline_ms=None, on_token=None, tenant=None, priority=5):
        """Admit + route one generation request; returns the replica
        engine's :class:`~mxnet_tpu.serving.decode.GenerateSession`.

        Shedding order (all typed, all counted under
        ``serving.shed.count{model=,reason=}``): pool ``Overloaded``
        past ``max_outstanding``; ``priority`` past the watermark for
        requests under the floor; ``quota`` for tenants at their bound;
        then the chosen replica's own engine admission applies."""
        tenant_key = tenant if tenant is not None else "*"
        with self._lock:
            if self._closed:
                raise MXNetError("replica pool %r is closed" % self.name)
            if self._total_outstanding >= self._max_outstanding:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="overload")
                raise Overloaded(
                    "pool %r overloaded: %d outstanding >= bound %d"
                    % (self.name, self._total_outstanding,
                       self._max_outstanding))
            if self._total_outstanding >= self._watermark \
                    and int(priority) < self._priority_floor:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="priority")
                raise Overloaded(
                    "pool %r past its priority watermark (%d/%d): "
                    "priority %d < floor %d shed"
                    % (self.name, self._total_outstanding,
                       self._watermark, priority, self._priority_floor))
            quota = self._quotas.get(tenant_key, self._quotas.get("*"))
            if quota is not None \
                    and self._tenant_out.get(tenant_key, 0) >= int(quota):
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="quota")
                raise QuotaExceeded(
                    "tenant %r at its quota of %d outstanding requests"
                    % (tenant_key, int(quota)))
            healthy = [r for r in self.replicas if r.state == ACTIVE]
            if not healthy:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="overload")
                raise Overloaded("pool %r has no healthy replicas "
                                 "(all quarantined/warming)" % self.name)
            r = min(healthy,
                    key=lambda x: self._outstanding[x.rid] / x.weight)
            self._outstanding[r.rid] += 1
            self._tenant_out[tenant_key] = \
                self._tenant_out.get(tenant_key, 0) + 1
            self._total_outstanding += 1
            r.routed += 1
            _telemetry.inc("serving.pool.routed.count", model=self.name,
                           replica=str(r.rid))
            _telemetry.set_gauge("serving.pool.outstanding",
                                 self._outstanding[r.rid],
                                 model=self.name, replica=str(r.rid))
        try:
            sess = r.engine.submit(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, deadline_ms=deadline_ms,
                on_token=on_token,
                on_done=self._make_done_hook(r.rid, tenant_key))
        except Exception:
            self._settle(r.rid, tenant_key)
            raise
        return sess

    def _make_done_hook(self, rid, tenant_key):
        return lambda _sess: self._settle(rid, tenant_key)

    def _settle(self, rid, tenant_key):
        with self._lock:
            self._outstanding[rid] = max(0, self._outstanding[rid] - 1)
            self._tenant_out[tenant_key] = \
                max(0, self._tenant_out.get(tenant_key, 0) - 1)
            self._total_outstanding = max(0, self._total_outstanding - 1)
            out = self._outstanding[rid]
        _telemetry.set_gauge("serving.pool.outstanding", out,
                             model=self.name, replica=str(rid))

    # -- replica health ----------------------------------------------------
    def _note_step_error(self, rid, exc):
        rewarm = False
        r = self.replicas[rid]
        with self._lock:
            r.failures += 1
            if r.state == ACTIVE and r.failures >= self._quarantine_after:
                r.state = QUARANTINED
                rewarm = True
        if rewarm:
            _telemetry.inc("serving.pool.quarantines.count",
                           model=self.name)
            _telemetry.set_gauge("serving.pool.replica_state",
                                 _STATE_GAUGE[QUARANTINED],
                                 model=self.name, replica=str(rid))
            _telemetry.event("serving.pool.quarantine", model=self.name,
                             replica=str(rid), failures=r.failures,
                             error=str(exc))
            _log.warning("pool %r: replica %d quarantined after %d "
                         "consecutive step failures (%s); re-warming in "
                         "the background", self.name, rid, r.failures,
                         exc)
            threading.Thread(target=self._rewarm, args=(rid,),
                             name="pool-rewarm-%s-%d" % (self.name, rid),
                             daemon=True).start()

    def _note_step_ok(self, rid):
        r = self.replicas[rid]
        with self._lock:
            r.failures = 0

    def _rewarm(self, rid):
        """Background quarantine recovery: shed what the replica holds,
        rebuild its compiled state through the warm-up path (persistent-
        cache loads when the PR 7 cache is armed), then return it to
        routing."""
        r = self.replicas[rid]
        with self._lock:
            if self._closed:
                # the pool was swapped out while the re-warm was
                # pending; the engine-level closed guard catches the
                # narrower race after this check
                return
            r.state = WARMING
        _telemetry.set_gauge("serving.pool.replica_state",
                             _STATE_GAUGE[WARMING], model=self.name,
                             replica=str(rid))
        try:
            r.engine.stop(drain=False)
            r.engine.rewarm()
            r.engine.start()
        except Exception as e:  # noqa: broad-except — a failed re-warm
            # must leave the replica quarantined (and the pool serving on
            # the others), never kill the recovery thread with the
            # replica stuck WARMING
            with self._lock:
                r.state = QUARANTINED
            _telemetry.set_gauge("serving.pool.replica_state",
                                 _STATE_GAUGE[QUARANTINED],
                                 model=self.name, replica=str(rid))
            _telemetry.event("serving.pool.rewarm_failed",
                             model=self.name, replica=str(rid),
                             error=str(e))
            _log.error("pool %r: re-warm of replica %d failed: %s",
                       self.name, rid, e)
            return
        with self._lock:
            r.state = ACTIVE
            r.failures = 0
        _telemetry.set_gauge("serving.pool.replica_state",
                             _STATE_GAUGE[ACTIVE], model=self.name,
                             replica=str(rid))
        _telemetry.event("serving.pool.rewarmed", model=self.name,
                         replica=str(rid))
        _log.info("pool %r: replica %d re-warmed and back in routing",
                  self.name, rid)

    # -- registry servable surface ----------------------------------------
    def pending_rows(self):
        """Queued + active sequences across every replica — the
        graceful-drain quiescence probe."""
        return sum(r.engine.pending_rows() for r in self.replicas)

    def outstanding(self):
        with self._lock:
            return self._total_outstanding

    def describe(self):
        with self._lock:
            reps = [dict(r.engine.describe(), state=r.state,
                         failures=r.failures, routed=r.routed,
                         outstanding=self._outstanding[r.rid],
                         weight=r.weight)
                    for r in self.replicas]
            total = self._total_outstanding
            tenants = dict(self._tenant_out)
        return {"name": self.name, "version": self.version,
                "kind": "generate", "replicas": reps,
                "outstanding": total,
                "max_outstanding": self._max_outstanding,
                "priority_floor": self._priority_floor,
                "quotas": dict(self._quotas),
                "tenants_outstanding": tenants}

    def close(self, drain=True):
        """Drain (by default) and permanently stop every replica — what
        the registry calls on the OLD pool after a pointer-flip swap.
        Returns True when every replica drained cleanly (False when any
        session was shed — shed sessions carry a typed error, they are
        never silently dropped)."""
        with self._lock:
            self._closed = True
        clean = True
        for r in self.replicas:
            try:
                if r.engine.close(drain=drain) is False:
                    clean = False
            except Exception:  # noqa: broad-except — closing one dead
                # replica must not leak the others
                clean = False
                _log.warning("pool %r: close of replica %d failed",
                             self.name, r.rid, exc_info=True)
        return clean


def lm_pool(cfg, params, n_replicas=2, devices=None, *, name="lm",
            version=1, engine_opts=None, **pool_opts):
    """Build a :class:`ReplicaPool` of
    :class:`~mxnet_tpu.serving.decode.DecodeEngine` replicas over a
    :mod:`~mxnet_tpu.models.transformer_lm` — the standard LM-serving
    stack (each replica gets the params committed to ITS device)."""
    opts = dict(engine_opts or {})

    def factory(device, replica_id):
        return DecodeEngine(cfg, params, device=device, name=name,
                            replica=replica_id, autostart=True, **opts)

    return ReplicaPool(factory, n_replicas=n_replicas, devices=devices,
                       name=name, version=version, **pool_opts)
