"""Dynamic micro-batcher — server-side request coalescing.

The TensorFlow system paper (Abadi et al., 2016) made server-side
request coalescing the step that turns a training framework into a
production inference system; TVM (Chen et al., 2018) showed that
shape-specialized compiled artifacts need explicit bucket management or
a compile storm eats the win.  This module is both halves for the XLA
predictor: concurrent requests queue into one worker that coalesces up
to ``max(buckets)`` rows or ``batch_timeout_us`` of waiting into ONE
padded device dispatch, padding the coalesced batch up to the nearest
pre-declared bucket so the shape-keyed jit cache (bounded by
``MXNET_PRED_CACHE_SIZE``, see :mod:`mxnet_tpu.predict`) sees only
``len(buckets)`` distinct shapes — ever.

Load discipline:

* **per-request deadlines** — a request whose deadline passes while it
  waits in the queue is shed with :class:`DeadlineExceeded` instead of
  wasting a device slot on an answer nobody is waiting for;
* **admission control** — a submit that would push the queue past
  ``max_queue_depth`` rows fast-fails with the typed :class:`Overloaded`
  error, so overload degrades into cheap rejections instead of a latency
  collapse for every in-flight request.

Telemetry (``serving.*`` family, labels ``model=<name>``):
``serving.request.count``, ``serving.shed.count{reason=...}``,
``serving.queue.depth`` gauge, ``serving.batch.size`` /
``serving.batch.latency_seconds`` / ``serving.request.latency_seconds``
histograms, ``serving.dispatch.count``.  The ``serving.dispatch`` fault
point (:mod:`mxnet_tpu.faults`) kills a device dispatch deterministically
so batch-error propagation is testable.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from .. import faults as _faults
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..base import MXNetError

__all__ = ["Overloaded", "DeadlineExceeded", "InvalidRequest", "Future",
           "DynamicBatcher", "LATENCY_BUCKETS", "BATCH_SIZE_BUCKETS"]

#: histogram bounds for serving latencies (seconds) — finer than the
#: telemetry default ladder so p50/p99 estimates are usable
LATENCY_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
#: histogram bounds for coalesced batch sizes (rows)
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class Overloaded(MXNetError):
    """Admission-control fast-fail: accepting the request would push the
    queue past its depth bound.  Clients should back off (HTTP 429)."""


class DeadlineExceeded(MXNetError):
    """The request's deadline expired before its batch dispatched (shed
    without device work), or a ``Future.result(timeout)`` wait ran out."""


class InvalidRequest(MXNetError):
    """Submit-time validation failure — the CLIENT's request is malformed
    (wrong feature dims, row count outside 1..max_batch_size, a scalar).
    A client error (HTTP 400), distinct from server-side failures."""


class Future:
    """Single-shot result holder for one queued request."""

    __slots__ = ("_ev", "_value", "_error", "_cancelled")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._error = None
        self._cancelled = False

    def set_result(self, value):
        self._value = value
        self._ev.set()

    def set_error(self, exc):
        self._error = exc
        self._ev.set()

    def done(self):
        return self._ev.is_set()

    def cancel(self):
        """Mark the request abandoned — its reader is gone.  A cancelled
        request is dropped by the worker before dispatch (shed reason
        ``abandoned``) and releases its admission rows instead of
        occupying the queue until a reader-less device dispatch.
        Returns False when the result already landed (best-effort: a
        result racing the cancel is harmless — the value sits unread)."""
        if self._ev.is_set():
            return False
        self._cancelled = True
        return True

    def cancelled(self):
        return self._cancelled

    def result(self, timeout=None):
        """Block for the batch carrying this request; re-raises the
        dispatch error (or the shed reason) when it failed."""
        if not self._ev.wait(timeout):
            raise DeadlineExceeded("no result within %ss" % timeout)
        if self._error is not None:
            raise self._error
        return self._value


class _Request:
    __slots__ = ("data", "n", "deadline", "future", "t_submit", "span")

    def __init__(self, data, n, deadline):
        self.data = data
        self.n = n
        self.deadline = deadline
        self.future = Future()
        self.t_submit = time.monotonic()
        self.span = _tracing.NULL_SPAN


class DynamicBatcher:
    """Coalesce concurrent requests into bucket-padded device dispatches.

    Parameters
    ----------
    dispatch_fn : callable(rows) -> array or tuple of arrays
        One device dispatch: ``rows`` is a ``(bucket, *feature)`` float32
        batch (real rows first, zero padding after); each returned array
        must keep row ``i`` of the output aligned with row ``i`` of the
        input (padded rows' outputs are discarded).
    buckets : tuple of int
        Pre-declared batch-size buckets; a coalesced batch of ``n`` rows
        pads up to the smallest bucket >= n.  ``max(buckets)`` is the
        coalescing limit (``max_batch_size``).
    batch_timeout_us : int
        How long the worker holds a non-full batch open for more arrivals
        before flushing (the latency/throughput knob).
    max_queue_depth : int
        Admission bound in ROWS; a submit past it raises
        :class:`Overloaded`.
    name : str
        Telemetry label (``model=<name>``).
    feature_shape : tuple, optional
        Per-row shape; when given, a mis-shaped request is rejected at
        ``submit`` (the one place the CLIENT gets the error) instead of
        poisoning a coalesced batch.
    """

    def __init__(self, dispatch_fn, buckets=(1, 8, 32),
                 batch_timeout_us=2000, max_queue_depth=128, name="model",
                 feature_shape=None):
        buckets = tuple(sorted({int(b) for b in buckets}))
        if not buckets or buckets[0] < 1:
            raise MXNetError("batcher needs >=1 positive batch bucket, "
                             "got %r" % (buckets,))
        self._dispatch_fn = dispatch_fn
        self.feature_shape = None if feature_shape is None \
            else tuple(feature_shape)
        self.buckets = buckets
        self.max_batch_size = buckets[-1]
        self.batch_timeout = batch_timeout_us / 1e6
        self.max_queue_depth = int(max_queue_depth)
        self.name = name
        self._queue = deque()
        self._depth = 0  # queued rows (admission unit)
        self._inflight_rows = 0  # rows inside the current dispatch
        self._cond = threading.Condition(threading.Lock())
        self._thread = None
        self._running = False
        self._closed = False
        #: total device dispatches (tests/bench assert coalescing on it)
        self.dispatches = 0
        # declare the families at zero so a clean server still exposes
        # them in snapshot()//metrics before the first request/shed —
        # with the SAME label dimensions the increments use, so the
        # family never carries mixed label sets
        _telemetry.inc("serving.request.count", 0, model=name)
        _telemetry.inc("serving.shed.count", 0, model=name,
                       reason="overload")
        _telemetry.inc("serving.shed.count", 0, model=name,
                       reason="deadline")
        _telemetry.inc("serving.shed.count", 0, model=name,
                       reason="abandoned")
        _telemetry.inc("serving.dispatch.count", 0, model=name)
        _telemetry.set_gauge("serving.queue.depth", 0, model=name)

    # -- client side -------------------------------------------------------
    def submit(self, data, deadline_ms=None):
        """Queue ``data`` (rows along axis 0) and return its
        :class:`Future`.  Raises :class:`Overloaded` at admission when
        the queue is past its depth bound."""
        data = np.asarray(data, np.float32)
        if data.ndim == 0:
            raise InvalidRequest("batcher requests are row batches; got "
                                 "a scalar")
        n = int(data.shape[0])
        if not 1 <= n <= self.max_batch_size:
            raise InvalidRequest(
                "request of %d rows outside 1..max_batch_size=%d (split "
                "oversized requests client-side)" % (n, self.max_batch_size))
        if self.feature_shape is not None \
                and tuple(data.shape[1:]) != self.feature_shape:
            raise InvalidRequest(
                "request rows shaped %s, model %r serves %s"
                % (tuple(data.shape[1:]), self.name, self.feature_shape))
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        req = _Request(data, n, deadline)
        # opened on the CALLER's thread (parents under an in-flight
        # HTTP span), closed from the worker thread at dispatch
        req.span = _tracing.start_span("serving.batch.request",
                                       stack=False, model=self.name,
                                       rows=n)
        with self._cond:
            if self._closed:
                req.span.end("error", reason="closed")
                raise MXNetError("serving %r is closed" % self.name)
            # counted only once accepted-or-shed: closed-batcher rejects
            # must not show as phantom unaccounted requests
            _telemetry.inc("serving.request.count", model=self.name)
            if self._depth + n > self.max_queue_depth:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="overload")
                req.span.end("shed", reason="overload")
                raise Overloaded(
                    "serving %r overloaded: queue %d rows + %d > bound %d"
                    % (self.name, self._depth, n, self.max_queue_depth))
            self._queue.append(req)
            self._depth += n
            _telemetry.set_gauge("serving.queue.depth", self._depth,
                                 model=self.name)
            self._cond.notify()
        return req.future

    #: default bound on blocking waits: queueing while the worker is not
    #: running is legitimate (stage, then ``start()``), so a forgotten
    #: ``start`` surfaces as a typed timeout instead of a silent hang
    DEFAULT_TIMEOUT = 60.0

    def predict(self, data, deadline_ms=None, timeout=DEFAULT_TIMEOUT):
        """Blocking convenience: ``submit`` + ``Future.result``.
        ``timeout=None`` waits forever.  A wait that times out CANCELS
        the request — an abandoned entry must not keep holding the
        admission bound down, nor be dispatched to a reader that is
        gone."""
        fut = self.submit(data, deadline_ms=deadline_ms)
        try:
            return fut.result(timeout)
        except DeadlineExceeded:
            fut.cancel()
            raise

    # -- worker side -------------------------------------------------------
    def start(self):
        """Start the coalescing worker thread (idempotent)."""
        with self._cond:
            if self._thread is not None:
                return self
            self._running = True
            self._thread = threading.Thread(
                target=self._serve_loop, name="batcher-%s" % self.name,
                daemon=True)
            self._thread.start()
        return self

    def stop(self, drain=True):
        """Stop the worker; ``drain`` dispatches whatever is still queued
        (synchronously), else pending futures fail with
        :class:`MXNetError`."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30)
        while True:
            batch = self._next_batch(block=False)
            if not batch:
                break
            try:
                if drain:
                    self._dispatch(batch)
                else:
                    err = MXNetError("serving %r stopped before dispatch"
                                     % self.name)
                    for r in batch:
                        r.span.end("shed", reason="stopped")
                        r.future.set_error(err)
            finally:
                with self._cond:
                    self._inflight_rows = 0
                    self._cond.notify_all()

    def close(self, drain=True):
        """Permanent :meth:`stop`: further ``submit`` calls fail fast
        with a typed error instead of queueing forever — what model
        unload/replace uses so stragglers holding the old reference
        don't hang until their timeout."""
        with self._cond:
            self._closed = True
        self.stop(drain=drain)

    def bucket_for(self, n):
        """Smallest declared bucket that fits ``n`` rows."""
        for b in self.buckets:
            if n <= b:
                return b
        raise MXNetError("batch of %d rows exceeds max bucket %d"
                         % (n, self.max_batch_size))

    def _serve_loop(self):
        while True:
            # the stop flag is written under the condition lock; reading
            # it bare can see a stale value on the worker thread
            # (graftlint lock-discipline), so take the lock for the check
            with self._cond:
                if not self._running:
                    return
            batch = self._next_batch(block=True)
            if batch:
                try:
                    self._dispatch(batch)
                finally:
                    with self._cond:
                        self._inflight_rows = 0
                        self._cond.notify_all()

    def pending_rows(self):
        """Rows queued plus rows inside the current device dispatch —
        0 means the batcher is quiescent.  The graceful-drain probe
        (``ServingHTTPServer.drain`` polls it to know when in-flight
        work has finished, docs/serving.md)."""
        with self._cond:
            return self._depth + self._inflight_rows

    def _next_batch(self, block):
        """Pop a coalesced run of requests: flush immediately when
        ``max_batch_size`` rows are ready, else ``batch_timeout`` after
        the first request was picked up.  Abandoned requests (a
        ``predict(timeout)`` wait that ran out cancels its future) are
        shed from the queue head here, releasing their admission rows —
        without the drop they would keep ``_depth`` inflated AND be
        dispatched to a reader that is gone; ones cancelled after the
        pop are skipped at dispatch."""
        with self._cond:
            while block and self._running and not self._queue:
                self._cond.wait(0.05)
            dropped = 0
            while self._queue and self._queue[0].future.cancelled():
                req = self._queue.popleft()
                self._depth -= req.n
                dropped += 1
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="abandoned")
                req.span.end("shed", reason="abandoned")
            if dropped:
                _telemetry.set_gauge("serving.queue.depth", self._depth,
                                     model=self.name)
            if not self._queue:
                return []
            batch = [self._queue.popleft()]
            rows = batch[0].n
            flush_at = time.monotonic() + self.batch_timeout
            while rows < self.max_batch_size:
                if self._queue:
                    if rows + self._queue[0].n > self.max_batch_size:
                        break  # head-of-line: goes in the next batch
                    req = self._queue.popleft()
                    batch.append(req)
                    rows += req.n
                    continue
                remaining = flush_at - time.monotonic()
                if remaining <= 0 or not block or not self._running:
                    break
                self._cond.wait(min(remaining, 0.05))
            self._depth -= rows
            self._inflight_rows = rows
            _telemetry.set_gauge("serving.queue.depth", self._depth,
                                 model=self.name)
            return batch

    def _dispatch(self, batch):
        now = time.monotonic()
        live = []
        for r in batch:
            if r.future.cancelled():
                # abandoned between pop and dispatch: no reader, no
                # device slot
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="abandoned")
                r.span.end("shed", reason="abandoned")
                continue
            if r.deadline is not None and now > r.deadline:
                _telemetry.inc("serving.shed.count", model=self.name,
                               reason="deadline")
                r.span.end("shed", reason="deadline")
                r.future.set_error(DeadlineExceeded(
                    "deadline expired %.1fms before dispatch"
                    % ((now - r.deadline) * 1e3)))
            else:
                live.append(r)
        if not live:
            return
        t0 = time.monotonic()
        # chrome-trace span for the coalesced dispatch (success or
        # error): a serving latency spike lines up on the SAME timeline
        # as compile/fit spans when the profiler runs
        prof = _profiler.running()
        span_us = _profiler.now_us() if prof else 0.0
        try:
            # batch assembly is inside the guard: a poison request (e.g.
            # mismatched feature dims past a shape-less dispatch_fn) must
            # fail ITS batch, never kill the worker thread
            n = sum(r.n for r in live)
            bucket = self.bucket_for(n)
            rows = live[0].data if len(live) == 1 \
                else np.concatenate([r.data for r in live], axis=0)
            if bucket > n:
                rows = np.concatenate(
                    [rows, np.zeros((bucket - n,) + rows.shape[1:],
                                    rows.dtype)], axis=0)
                _telemetry.inc("serving.batch.padded_rows", bucket - n,
                               model=self.name)
            _telemetry.observe("serving.batch.size", n,
                               buckets=BATCH_SIZE_BUCKETS, model=self.name)
            if _faults.should_fire("serving.dispatch"):
                raise _faults.FaultInjected(
                    "fault 'serving.dispatch': device dispatch of model "
                    "%r killed" % self.name)
            outs = self._dispatch_fn(rows)
            outs = [np.asarray(o) for o in
                    (outs if isinstance(outs, (list, tuple)) else [outs])]
            results = []
            off = 0
            for r in live:
                sl = [o[off:off + r.n] for o in outs]
                results.append(sl[0] if len(sl) == 1 else sl)
                off += r.n
        except Exception as e:
            # one bad dispatch fails ITS requests; the worker survives
            # to serve the next batch
            _telemetry.inc("serving.error.count", model=self.name)
            for r in live:
                r.span.end("error", error=type(e).__name__)
                r.future.set_error(e)
            if prof:
                _profiler.record("serving:%s:dispatch_error" % self.name,
                                 "serving", span_us, _profiler.now_us())
            return
        if prof:
            _profiler.record("serving:%s:dispatch" % self.name,
                             "serving", span_us, _profiler.now_us())
        self.dispatches += 1
        _telemetry.inc("serving.dispatch.count", model=self.name)
        _telemetry.observe("serving.batch.latency_seconds",
                           time.monotonic() - t0, buckets=LATENCY_BUCKETS,
                           model=self.name)
        done_t = time.monotonic()
        for r, res in zip(live, results):
            r.span.end("ok", rows=r.n, bucket=bucket)
            r.future.set_result(res)
            _telemetry.observe("serving.request.latency_seconds",
                               done_t - r.t_submit,
                               buckets=LATENCY_BUCKETS, model=self.name)
