"""Fleet control plane — SLO-driven autoscaling, placement, and
supervised serving (docs/serving.md "Fleet control plane").

PR 9 gave the decode tier telemetry, PR 12 gave it circuit breakers and
zero-cost session migration, PR 15 gave training a supervisor — this
module is the loop that WATCHES those signals and ACTS (ROADMAP open
item 5): a :class:`FleetController` ticks over every
:class:`~mxnet_tpu.serving.pool.ReplicaPool` registered in a
:class:`~mxnet_tpu.serving.registry.ModelRegistry` and closes four
loops per model:

* **autoscaling** — an :class:`AutoscalePolicy` compares the windowed
  TTFT p99 (``serving.decode.ttft_seconds`` bucket-count deltas, not
  the cumulative process history) and admission pressure against
  ``MXNET_FLEET_SLO_TTFT_MS``; sustained breach grows the pool
  (``ReplicaPool.add_replica`` — the engine is warmed from the PR 7
  manifests BEFORE it is published to routing), sustained slack
  shrinks it (``remove_replica`` — live sessions migrate via the PR 12
  ``resume()`` transport, bit-identical, budget-free).  Hysteresis
  (breach/slack streaks) plus a post-scale cooldown keep the loop from
  flapping.
* **placement** — a :class:`DeviceFleet` bin-packs every model's
  replicas onto the shared device fleet
  (``MXNET_FLEET_REPLICAS_PER_DEVICE`` per device) and periodically
  proposes a move from the most- to the least-loaded device; a move is
  add-on-target first, then drain-by-migration — replicas are movable
  at zero request cost.
* **supervised serving** — per-replica liveness via the decode
  engine's heartbeat (``DecodeEngine.heartbeat_age``) and the pool's
  hard-kill flag; a dead or wedged replica is replaced (same device,
  warmed before routing, sessions adopted by survivors meanwhile)
  under the SAME backoff + ``MXNET_RESTART_BUDGET`` discipline as the
  training sentinel; a crash-looping model exhausts the budget into
  QUARANTINE — the controller stops replacing and says so — instead of
  thrashing.
* **priority shedding** — when the SLO is breached and the fleet
  cannot grow (device capacity or ``MXNET_FLEET_MAX_REPLICAS``), the
  controller turns on the pool's admission pressure
  (``set_shed_pressure``): requests below the priority floor shed
  TYPED from the first outstanding request.  In-flight generations are
  never dropped — this is admission control, not load shedding by
  abandonment.

Every decision lands in the ``serving.fleet.*`` telemetry family and a
bounded ring the frontend serves at ``GET /fleet`` (plus a summary
block in ``/healthz``).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from .. import telemetry as _telemetry
from ..base import MXNetError
from ..compile_cache import _env_float, _env_int
from .pool import ACTIVE

__all__ = ["Observation", "AutoscalePolicy", "DeviceFleet",
           "FleetController", "HOLD", "SCALE_UP", "SCALE_DOWN", "SHED",
           "UNSHED"]

_log = logging.getLogger(__name__)

#: policy decisions (``AutoscalePolicy.decide`` return values)
HOLD = "hold"
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
SHED = "shed"
UNSHED = "unshed"

#: the TTFT histogram the observation window diffs
_TTFT_HIST = "serving.decode.ttft_seconds"


class Observation:
    """One tick's per-model load read — what the policy decides from.

    Plain data so the decision logic is testable from synthetic
    snapshots (no devices, no HTTP): ``ttft_p99_ms`` is the windowed
    p99 (None before the first window closes or with telemetry off),
    ``queue_frac`` the pool admission fill (outstanding /
    max_outstanding), ``occupancy`` outstanding over live decode
    slots, ``replicas`` the ACTIVE replica count, and ``can_grow``
    whether the device fleet has headroom for one more."""

    __slots__ = ("ttft_p99_ms", "queue_frac", "occupancy", "replicas",
                 "can_grow")

    def __init__(self, ttft_p99_ms=None, queue_frac=0.0, occupancy=0.0,
                 replicas=1, can_grow=True):
        self.ttft_p99_ms = ttft_p99_ms
        self.queue_frac = float(queue_frac)
        self.occupancy = float(occupancy)
        self.replicas = int(replicas)
        self.can_grow = bool(can_grow)

    def __repr__(self):
        return ("Observation(ttft_p99_ms=%r, queue_frac=%.3f, "
                "occupancy=%.3f, replicas=%d, can_grow=%r)"
                % (self.ttft_p99_ms, self.queue_frac, self.occupancy,
                   self.replicas, self.can_grow))


class AutoscalePolicy:
    """Hysteresis + cooldown autoscaling decisions, one instance per
    model.  Pure decision logic over :class:`Observation` snapshots —
    no telemetry reads, no pool calls, no threads — so unit tests
    drive it tick by tick.

    A tick is a BREACH when the windowed TTFT p99 exceeds the SLO
    target or admission fill crosses ``queue_high``; ``breach_ticks``
    consecutive breaches scale up (or, when the fleet cannot grow,
    turn shedding on — shed-before-fail, never scale into capacity
    that is not there).  A tick is SLACK when TTFT sits under
    ``slack_frac`` of the SLO with low occupancy and a near-empty
    queue; ``slack_ticks`` consecutive slack ticks scale down, never
    below ``min_replicas``.  Any scale starts a ``cooldown_s`` window
    during which further scaling holds — the no-flap guarantee is the
    streaks + cooldown together.  Shedding turns off only after the
    breach fully clears for ``breach_ticks`` ticks."""

    def __init__(self, slo_ttft_ms=None, breach_ticks=None,
                 slack_ticks=None, cooldown_s=None, min_replicas=1,
                 max_replicas=None, slack_frac=0.5, queue_high=0.85,
                 occupancy_low=0.5):
        self.slo_ttft_ms = float(slo_ttft_ms) if slo_ttft_ms is not None \
            else _env_float("MXNET_FLEET_SLO_TTFT_MS", 500.0)
        self.breach_ticks = int(breach_ticks) if breach_ticks is not None \
            else _env_int("MXNET_FLEET_BREACH_TICKS", 3)
        self.slack_ticks = int(slack_ticks) if slack_ticks is not None \
            else _env_int("MXNET_FLEET_SLACK_TICKS", 10)
        self.cooldown_s = float(cooldown_s) if cooldown_s is not None \
            else _env_float("MXNET_FLEET_COOLDOWN_MS", 5000.0) / 1e3
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = int(max_replicas) if max_replicas is not None \
            else _env_int("MXNET_FLEET_MAX_REPLICAS", 8)
        self.slack_frac = float(slack_frac)
        self.queue_high = float(queue_high)
        self.occupancy_low = float(occupancy_low)
        self.breach_streak = 0
        self.slack_streak = 0
        self.clear_streak = 0
        self.last_scale = None
        self.shedding = False

    def decide(self, obs, now):
        """One control decision from one :class:`Observation`; returns
        ``(action, info)`` with ``action`` one of :data:`HOLD` /
        :data:`SCALE_UP` / :data:`SCALE_DOWN` / :data:`SHED` /
        :data:`UNSHED` and ``info`` the evidence (streaks, the breach
        signal, cooldown state) for the decision ring."""
        breach = (obs.ttft_p99_ms is not None
                  and obs.ttft_p99_ms > self.slo_ttft_ms) \
            or obs.queue_frac >= self.queue_high
        slack = (not breach
                 and obs.occupancy <= self.occupancy_low
                 and obs.queue_frac <= 0.25
                 and (obs.ttft_p99_ms is None
                      or obs.ttft_p99_ms
                      <= self.slack_frac * self.slo_ttft_ms))
        if breach:
            self.breach_streak += 1
            self.slack_streak = 0
            self.clear_streak = 0
        else:
            self.breach_streak = 0
            self.clear_streak += 1
            self.slack_streak = self.slack_streak + 1 if slack else 0
        cooling = self.last_scale is not None \
            and (now - self.last_scale) < self.cooldown_s
        info = {"ttft_p99_ms": obs.ttft_p99_ms,
                "queue_frac": round(obs.queue_frac, 3),
                "occupancy": round(obs.occupancy, 3),
                "replicas": obs.replicas, "breach": breach,
                "breach_streak": self.breach_streak,
                "slack_streak": self.slack_streak,
                "cooldown": cooling, "shedding": self.shedding}

        if self.breach_streak >= self.breach_ticks:
            grown = obs.replicas < self.max_replicas and obs.can_grow
            if grown and not cooling:
                self.last_scale = now
                self.breach_streak = 0
                return SCALE_UP, info
            if not grown and not self.shedding:
                # fleet exhausted: shed by priority instead of failing
                self.shedding = True
                info["shedding"] = True
                return SHED, info
            return HOLD, info  # cooling down, or already shedding
        if self.shedding and self.clear_streak >= self.breach_ticks:
            self.shedding = False
            info["shedding"] = False
            return UNSHED, info
        if self.slack_streak >= self.slack_ticks \
                and obs.replicas > self.min_replicas and not cooling \
                and not self.shedding:
            self.last_scale = now
            self.slack_streak = 0
            return SCALE_DOWN, info
        return HOLD, info


class DeviceFleet:
    """The shared placement book: which device hosts which (model,
    replica), with a per-device replica cap
    (``MXNET_FLEET_REPLICAS_PER_DEVICE``).  Pure bookkeeping — it
    never touches engines — so the bin-packing is unit-testable and
    the controller's actuators (``add_replica(device=...)``) stay the
    only side-effecting path."""

    def __init__(self, devices=None, per_device=None):
        if devices is None:
            import jax

            devices = jax.devices()
        self._devices = list(devices)
        if not self._devices:
            raise MXNetError("DeviceFleet needs >= 1 device")
        self._per = int(per_device) if per_device is not None \
            else _env_int("MXNET_FLEET_REPLICAS_PER_DEVICE", 4)
        self._lock = threading.Lock()
        self._placements = {}  # (model, rid) -> device index

    def _loads_locked(self):
        loads = [0] * len(self._devices)
        for idx in self._placements.values():
            loads[idx] += 1
        return loads

    def _index_of_locked(self, device):
        for i, d in enumerate(self._devices):
            if d is device or str(d) == str(device):
                return i
        return None

    def least_loaded(self):
        """The device a new replica should land on, or None when every
        device is at its cap."""
        with self._lock:
            loads = self._loads_locked()
            idx = min(range(len(self._devices)), key=lambda i: loads[i])
            if loads[idx] >= self._per:
                return None
            return self._devices[idx]

    def assign(self, model, rid, device):
        """Record that ``(model, rid)`` runs on ``device`` (placements
        discovered at adoption time land here too — unknown devices
        count against device 0 rather than being lost)."""
        with self._lock:
            idx = self._index_of_locked(device)
            self._placements[(model, rid)] = 0 if idx is None else idx

    def release(self, model, rid):
        with self._lock:
            self._placements.pop((model, rid), None)

    def release_model(self, model):
        """Drop every placement of ``model`` (pointer-flip swap: the
        new pool's replicas re-seed)."""
        with self._lock:
            for key in [k for k in self._placements if k[0] == model]:
                del self._placements[key]

    def device_of(self, model, rid):
        with self._lock:
            idx = self._placements.get((model, rid))
            return None if idx is None else self._devices[idx]

    def capacity_left(self):
        with self._lock:
            return self._per * len(self._devices) - len(self._placements)

    def suggest_move(self):
        """One rebalancing move ``(model, rid, target_device)`` from
        the most- to the least-loaded device, or None when the packing
        is already within one replica of even."""
        with self._lock:
            if not self._placements:
                return None
            loads = self._loads_locked()
            hi = max(range(len(self._devices)), key=lambda i: loads[i])
            lo = min(range(len(self._devices)), key=lambda i: loads[i])
            if loads[hi] - loads[lo] <= 1 or loads[lo] >= self._per:
                return None
            for (model, rid), idx in sorted(self._placements.items(),
                                            key=lambda kv: str(kv[0])):
                if idx == hi:
                    return model, rid, self._devices[lo]
            return None

    def describe(self):
        with self._lock:
            loads = self._loads_locked()
            placements = {"%s/%s" % k: str(self._devices[v])
                          for k, v in sorted(self._placements.items(),
                                             key=lambda kv: str(kv[0]))}
        return {"devices": [str(d) for d in self._devices],
                "per_device": self._per, "loads": loads,
                "placements": placements}


class _ModelState:
    """Per-managed-pool controller bookkeeping (mutated only with the
    controller's lock held or from the controller thread; replaced
    wholesale on a version swap)."""

    def __init__(self, pool, policy, now, budget):
        self.pool = pool
        self.policy = policy
        self.ttft_counts = None   # last hist_state counts (the window)
        self.ttft_total = 0
        self.breach_since = None  # SLO-recovery stopwatch
        self.restarts_used = 0
        self.restart_budget = budget
        self.last_restart = None
        self.last_healthy = now
        self.backoff = 0.0
        self.quarantined = False


class FleetController:
    """The closed control loop: a monitor thread ticks every
    ``MXNET_FLEET_INTERVAL_MS`` over the registry's decode pools —
    supervise (replace dead/wedged replicas), observe (windowed TTFT
    p99 + admission pressure), decide (:class:`AutoscalePolicy`), act
    (scale / shed / rebalance through the pool's actuators).  All
    controller state lives behind ``self._lock``; pool and registry
    locks are only ever taken while it is NOT held by the same
    call-path's callee (the pool never calls back into the
    controller), so there is no lock-order cycle."""

    def __init__(self, registry, fleet=None, interval_ms=None,
                 heartbeat_timeout=None, restart_budget=None,
                 backoff_base=0.5, backoff_max=30.0, healthy_reset_s=60.0,
                 rebalance_every_s=10.0, policy_opts=None):
        self._registry = registry
        self._fleet = fleet if fleet is not None else DeviceFleet()
        self._interval = (float(interval_ms) if interval_ms is not None
                          else _env_float("MXNET_FLEET_INTERVAL_MS",
                                          500.0)) / 1e3
        hb = heartbeat_timeout if heartbeat_timeout is not None \
            else _env_float("MXNET_FLEET_HEARTBEAT_S", 0.0)
        self._hb_timeout = float(hb) or None  # 0 / None: liveness by
        # the pool's hard-kill flag only (CI machines stall arbitrarily)
        self._budget = int(restart_budget) if restart_budget is not None \
            else _env_int("MXNET_RESTART_BUDGET", 5)
        self._backoff_base = float(backoff_base)
        self._backoff_max = float(backoff_max)
        self._healthy_reset = float(healthy_reset_s)
        self._rebalance_every = float(rebalance_every_s)
        self._policy_opts = dict(policy_opts or {})
        self._lock = threading.Lock()
        self._models = {}         # name -> _ModelState
        self._decisions = deque(maxlen=64)
        self._ticks = 0
        self._last_rebalance = 0.0
        self._closed = False
        self._thread = None
        self._stop = threading.Event()
        attach = getattr(registry, "attach_controller", None)
        if attach is not None:
            attach(self)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._lock:
            if self._closed:
                raise MXNetError("fleet controller is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="fleet-controller",
                    daemon=True)
                self._thread.start()
        return self

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread = self._thread
        self._stop.set()
        if thread is not None:
            thread.join(timeout=10)

    stop = close

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception:  # noqa: broad-except — the control loop
                # must outlive any single bad tick; the failure is
                # telemetry, not a dead fleet
                _log.exception("fleet controller: tick failed")
                _telemetry.inc("serving.fleet.tick_errors.count")

    # -- the loop body ------------------------------------------------------
    def tick(self, now=None):
        """One supervise→observe→decide→act pass over every managed
        pool (public so tests single-step the loop without the
        thread)."""
        now = time.monotonic() if now is None else now
        pools = [(m.name, m) for m in self._registry.models()
                 if hasattr(m, "add_replica")]
        with self._lock:
            if self._closed:
                return
            self._ticks += 1
            live = {name for name, _ in pools}
            for name in [n for n in self._models if n not in live]:
                del self._models[name]
                self._fleet.release_model(name)
            states = []
            for name, pool in pools:
                st = self._models.get(name)
                if st is None or st.pool is not pool:
                    st = self._new_state(name, pool, now)
                    self._models[name] = st
                states.append((name, st))
        for name, st in states:
            if st is None:
                continue
            self._supervise(name, st, now)
            obs = self._observe(name, st, now)
            action, info = st.policy.decide(obs, now)
            self._act(name, st, obs, action, info, now)
        self._maybe_rebalance(now)

    def _new_state(self, name, pool, now):
        """Start managing ``pool`` under ``name``: fresh policy +
        restart budget, placements seeded from the replicas' current
        devices.  Touches no controller state — the caller owns the
        ``self._models`` write (under the controller lock)."""
        self._fleet.release_model(name)
        policy = AutoscalePolicy(**self._policy_opts)
        st = _ModelState(pool, policy, now, self._budget)
        for r in list(pool.replicas):
            self._fleet.assign(name, r.rid, r.device)
        _telemetry.event("serving.fleet.adopt", model=name,
                         replicas=len(pool.replicas))
        _log.info("fleet: managing pool %r (%d replica(s))", name,
                  len(pool.replicas))
        return st

    def on_register(self, name, servable):
        """Registry hook, fired after a pointer-flip swap: drop the old
        pool's state so the next tick adopts the successor with fresh
        placements (a non-pool servable simply stops being managed)."""
        with self._lock:
            self._models.pop(name, None)
            self._fleet.release_model(name)

    # -- supervise ----------------------------------------------------------
    def _supervise(self, name, st, now):
        """Replace dead / wedged replicas under the restart-budget +
        backoff discipline; quarantine the model's replacement loop
        when the budget is spent (sessions were already adopted by the
        survivors at kill time — the pool did that)."""
        suspects = []
        for r in list(st.pool.replicas):
            if r.state != ACTIVE and not r.dead:
                continue
            stale = None
            if not r.dead and self._hb_timeout:
                age_fn = getattr(r.engine, "heartbeat_age", None)
                age = age_fn() if age_fn is not None else None
                if age is not None and age > self._hb_timeout:
                    stale = age
            if r.dead or stale is not None:
                suspects.append((r, stale))
        if not suspects:
            if st.last_restart is not None \
                    and now - st.last_restart >= self._healthy_reset \
                    and st.restarts_used:
                # a quiet stretch pays the budget back (the training
                # sentinel's healthy_reset_s, applied to the fleet)
                st.restarts_used = 0
                st.backoff = 0.0
                _telemetry.event("serving.fleet.budget_reset", model=name)
            return
        for r, stale in suspects:
            if st.quarantined:
                return
            if st.restarts_used >= st.restart_budget:
                st.quarantined = True
                _telemetry.inc("serving.fleet.quarantines.count",
                               model=name)
                self._note(name, "quarantine", replica=r.rid,
                           restarts=st.restarts_used,
                           budget=st.restart_budget)
                _log.error(
                    "fleet: model %r exhausted its restart budget "
                    "(%d) — replica replacement QUARANTINED, serving "
                    "on the survivors", name, st.restart_budget)
                return
            if st.last_restart is not None \
                    and now - st.last_restart < st.backoff:
                return  # backing off; re-check next tick
            dev = self._fleet.device_of(name, r.rid)
            try:
                st.pool.remove_replica(r.rid, migrate=True)
            except MXNetError:
                pass  # already removed by a racing actor
            self._fleet.release(name, r.rid)
            st.restarts_used += 1
            st.last_restart = now
            st.backoff = min(self._backoff_max,
                             self._backoff_base * (2 ** (st.restarts_used
                                                         - 1)))
            try:
                new_rid = st.pool.add_replica(device=dev)
            except Exception as e:  # noqa: broad-except — a failed
                # rebuild (device gone, OOM) burns budget and backs
                # off; the pool keeps serving on the survivors
                _telemetry.inc("serving.fleet.restart_failures.count",
                               model=name)
                self._note(name, "restart_failed", replica=r.rid,
                           error=str(e))
                _log.warning("fleet: replacing replica %d of %r failed",
                             r.rid, name, exc_info=True)
                continue
            self._fleet.assign(name, new_rid, dev)
            _telemetry.inc("serving.fleet.restarts.count", model=name)
            self._note(name, "restart", replica=r.rid, new_replica=new_rid,
                       wedged_s=stale, restarts=st.restarts_used,
                       budget=st.restart_budget)
            _log.warning(
                "fleet: replica %d of %r %s — replaced by replica %d "
                "on %s (restart %d/%d)", r.rid, name,
                "heartbeat stale %.1fs" % stale if stale is not None
                else "dead", new_rid, dev, st.restarts_used,
                st.restart_budget)

    # -- observe ------------------------------------------------------------
    def _observe(self, name, st, now):
        out, max_out, _pressure = st.pool.admission_state()
        queue_frac = out / float(max(1, max_out))
        ttft_ms = None
        hs = _telemetry.hist_state(_TTFT_HIST, model=name)
        if hs is not None:
            if st.ttft_counts is not None and hs["count"] > st.ttft_total:
                delta = [a - b for a, b in zip(hs["counts"],
                                               st.ttft_counts)]
                q = _telemetry.quantile_from_counts(
                    hs["buckets"], delta, 0.99, lo=0.0, hi=hs["max"])
                if q is not None:
                    ttft_ms = q * 1e3
            st.ttft_counts = list(hs["counts"])
            st.ttft_total = hs["count"]
        live = [r for r in st.pool.replicas
                if r.state == ACTIVE and not r.dead]
        slots = sum(max(1, getattr(r.engine, "slots", 1)) for r in live)
        obs = Observation(
            ttft_p99_ms=ttft_ms, queue_frac=queue_frac,
            occupancy=out / float(max(1, slots)),
            replicas=len(live),
            can_grow=self._fleet.capacity_left() > 0)
        _telemetry.set_gauge("serving.fleet.replicas", obs.replicas,
                             model=name)
        if ttft_ms is not None:
            _telemetry.set_gauge("serving.fleet.ttft_p99_ms", ttft_ms,
                                 model=name)
        # SLO breach / recovery stopwatch — the chaos acceptance's
        # "recovers within the pinned window" clock
        slo = st.policy.slo_ttft_ms
        if ttft_ms is not None and ttft_ms > slo:
            if st.breach_since is None:
                st.breach_since = now
                _telemetry.inc("serving.fleet.slo_breaches.count",
                               model=name)
                self._note(name, "slo_breach", ttft_p99_ms=ttft_ms,
                           slo_ttft_ms=slo)
        elif ttft_ms is not None and st.breach_since is not None:
            recovery = now - st.breach_since
            st.breach_since = None
            _telemetry.observe("serving.fleet.slo_recovery_seconds",
                               recovery, model=name)
            self._note(name, "slo_recovery", ttft_p99_ms=ttft_ms,
                       recovery_ms=round(recovery * 1e3, 1))
        return obs

    # -- act ----------------------------------------------------------------
    def _act(self, name, st, obs, action, info, now):
        if action == HOLD:
            return
        if action == SCALE_UP:
            dev = self._fleet.least_loaded()
            if dev is None:  # raced to full between observe and act
                return
            try:
                rid = st.pool.add_replica(device=dev)
            except Exception as e:  # noqa: broad-except — a failed
                # grow must not kill the loop; the breach streak will
                # re-trigger
                self._note(name, "scale_up_failed", error=str(e))
                _log.warning("fleet: scale-up of %r failed", name,
                             exc_info=True)
                return
            self._fleet.assign(name, rid, dev)
            _telemetry.inc("serving.fleet.scale_ups.count", model=name)
            self._note(name, SCALE_UP, replica=rid, device=str(dev),
                       **info)
            _log.info("fleet: %r scaled UP to %d replicas (TTFT p99 "
                      "%s ms, queue %.0f%%)", name, obs.replicas + 1,
                      "%.1f" % obs.ttft_p99_ms
                      if obs.ttft_p99_ms is not None else "n/a",
                      100 * obs.queue_frac)
        elif action == SCALE_DOWN:
            live = [r for r in st.pool.replicas
                    if r.state == ACTIVE and not r.dead]
            if len(live) <= st.policy.min_replicas:
                return
            victim = max(live, key=lambda r: r.rid)  # youngest first
            try:
                st.pool.remove_replica(victim.rid, migrate=True)
            except MXNetError:
                return  # a racing actor already removed it
            self._fleet.release(name, victim.rid)
            _telemetry.inc("serving.fleet.scale_downs.count", model=name)
            self._note(name, SCALE_DOWN, replica=victim.rid, **info)
            _log.info("fleet: %r scaled DOWN to %d replicas (sustained "
                      "slack)", name, len(live) - 1)
        elif action == SHED:
            st.pool.set_shed_pressure(True)
            _telemetry.inc("serving.fleet.sheds.count", model=name)
            self._note(name, SHED, **info)
            _log.warning("fleet: %r exhausted the fleet at max scale — "
                         "priority shedding ON", name)
        elif action == UNSHED:
            st.pool.set_shed_pressure(False)
            self._note(name, UNSHED, **info)
            _log.info("fleet: %r breach cleared — priority shedding off",
                      name)

    # -- rebalance ----------------------------------------------------------
    def _maybe_rebalance(self, now):
        with self._lock:
            if now - self._last_rebalance < self._rebalance_every:
                return
            self._last_rebalance = now
            states = dict(self._models)
        move = self._fleet.suggest_move()
        if move is None:
            return
        model, rid, dst = move
        st = states.get(model)
        if st is None or st.quarantined:
            return
        # add on the target FIRST (warmed before routing), then drain
        # the source by migration — the move costs no request anything
        try:
            new_rid = st.pool.add_replica(device=dst)
        except Exception:  # noqa: broad-except — no capacity to stage
            # the move safely; try again next period
            _log.warning("fleet: rebalance add for %r failed", model,
                         exc_info=True)
            return
        self._fleet.assign(model, new_rid, dst)
        try:
            st.pool.remove_replica(rid, migrate=True)
        except MXNetError:
            pass  # already gone; the add still improved the packing
        self._fleet.release(model, rid)
        _telemetry.inc("serving.fleet.rebalances.count", model=model)
        self._note(model, "rebalance", replica=rid, new_replica=new_rid,
                   device=str(dst))
        _log.info("fleet: rebalanced %r replica %d -> %d on %s", model,
                  rid, new_rid, dst)

    # -- introspection ------------------------------------------------------
    def _note(self, model, action, **info):
        entry = {"t": time.time(), "model": model, "action": action}
        entry.update(info)
        with self._lock:
            self._decisions.append(entry)
        _telemetry.event("serving.fleet.decision", model=model,
                         action=action, **info)

    def decisions(self):
        """The bounded decision ring, oldest first (``GET /fleet``)."""
        with self._lock:
            return list(self._decisions)

    def describe(self):
        """Structured controller card for ``GET /fleet`` and the
        ``/healthz`` fleet block."""
        with self._lock:
            ticks = self._ticks
            running = self._thread is not None and not self._closed
            models = {
                name: {"quarantined": st.quarantined,
                       "restarts_used": st.restarts_used,
                       "restart_budget": st.restart_budget,
                       "breaching": st.breach_since is not None,
                       "shedding": st.policy.shedding,
                       "slo_ttft_ms": st.policy.slo_ttft_ms}
                for name, st in sorted(self._models.items())}
            decisions = list(self._decisions)
        return {"running": running, "ticks": ticks,
                "interval_ms": self._interval * 1e3,
                "models": models, "fleet": self._fleet.describe(),
                "decisions": decisions[-16:]}
