"""Versioned multi-model registry — atomic load/reload/unload + warm-up.

A servable model is published to a directory with :func:`save_model`:
``symbol.json`` and ``model.params`` first, ``manifest.json`` LAST via
:func:`mxnet_tpu.base.atomic_write` (PR 1's checkpoint-manifest
convention, fault point ``serving.model.write``).  The manifest carries
sha256 checksums of the payload files, so a reader either loads a
complete, consistent publish or detects a torn one — never silently
serves half-written weights.

:class:`ModelRegistry` maps ``name -> ServedModel``.  ``load``/``reload``
builds and WARMS the new version entirely off-registry — per-bucket
warm-up compilation at load time means first requests never eat an XLA
trace — then swaps it in under the registry lock; any failure (bad
checksum, missing params, injected fault) leaves the previous version
serving untouched.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading

import numpy as np

from .. import compile_cache as _compile_cache
from .. import predict as _predict
from .. import telemetry as _telemetry
from ..base import MXNetError, atomic_write, atomic_write_bytes
from .batcher import DynamicBatcher

__all__ = ["UnknownModel", "ServedModel", "ModelRegistry", "save_model",
           "MANIFEST", "WARMUP_MANIFEST"]

#: the publish marker: readers only trust a directory carrying one
MANIFEST = "manifest.json"

#: serializes warm-up build recording across concurrently loading
#: models: compile_cache's recording scope and hit/miss counters are
#: process-global, so two interleaved warm-ups would cross-contaminate
#: each other's manifest entries and cold/warm stats.  Warm-up is a
#: rare load-time event; serializing it is the cheap correct trade.
_warmup_record_lock = threading.Lock()

#: compile-once warm-up manifest (docs/how_to/perf.md "Compile once"):
#: records every executable a load compiled (kind / shape signature /
#: HLO fingerprint) so the NEXT load of the same directory pre-builds
#: them all as persistent-cache loads — version-independent, since the
#: compiled program depends on symbol+shapes, not the weights
WARMUP_MANIFEST = "warmup.json"


class UnknownModel(MXNetError):
    """Request for a model name the registry has not loaded (HTTP 404)."""


def _sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_model(model_dir, symbol_json, param_blob, input_shape,
               data_name="data", buckets=(1, 8, 32), version=1, name=None):
    """Publish a servable model directory atomically; returns the
    manifest dict.

    ``input_shape`` is the PER-SAMPLE feature shape (no batch dim);
    ``buckets`` declares the batch-size buckets the server will compile.
    Payload files are VERSION-QUALIFIED (``symbol-v2.json``, ...) and
    written first; the checksummed manifest goes last under the
    ``serving.model.write`` fault point.  A publisher dying anywhere
    mid-publish therefore leaves the previous version fully loadable on
    disk — new payloads never clobber old ones, and the old manifest
    still references intact files.  After a successful publish, payload
    files of superseded versions are garbage-collected best-effort.
    """
    os.makedirs(model_dir, exist_ok=True)
    if hasattr(symbol_json, "tojson"):  # a Symbol
        symbol_json = symbol_json.tojson()
    sym_bytes = symbol_json.encode() if isinstance(symbol_json, str) \
        else bytes(symbol_json)
    version = int(version)
    sym_name = "symbol-v%d.json" % version
    par_name = "model-v%d.params" % version
    atomic_write_bytes(os.path.join(model_dir, sym_name), sym_bytes)
    atomic_write_bytes(os.path.join(model_dir, par_name),
                       bytes(param_blob))
    manifest = {
        "name": name or os.path.basename(os.path.abspath(model_dir)),
        "version": version,
        "symbol": sym_name,
        "params": par_name,
        "data_name": data_name,
        "input_shape": [int(d) for d in input_shape],
        "buckets": sorted({int(b) for b in buckets}),
        "sha256": {
            sym_name: _sha256(os.path.join(model_dir, sym_name)),
            par_name: _sha256(os.path.join(model_dir, par_name)),
        },
    }

    def _write(tmp):
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)

    atomic_write(os.path.join(model_dir, MANIFEST), _write,
                 fault_point="serving.model.write")
    # the publish is durable; drop superseded payloads (orphans from a
    # crashed publish get collected by the next successful one)
    for fname in os.listdir(model_dir):
        if fname in (sym_name, par_name, MANIFEST):
            continue
        if fname.startswith(("symbol-v", "model-v")) \
                and ".tmp-" not in fname:
            # never touch a racing publisher's atomic_write temp files
            try:
                os.unlink(os.path.join(model_dir, fname))
            except OSError:  # noqa - best-effort GC, publish already durable
                pass
    return manifest


class ServedModel:
    """One loaded, warm model version: a :class:`~mxnet_tpu.predict.
    Predictor` cycled across the declared batch buckets (all shapes held
    by its bounded executor cache) plus the model's
    :class:`~mxnet_tpu.serving.batcher.DynamicBatcher`."""

    def __init__(self, name, symbol_json, param_blob, input_shape,
                 data_name="data", buckets=(1, 8, 32), version=1,
                 ctx=None, batch_timeout_us=2000, max_queue_depth=128,
                 autostart=True, warmup_manifest=None):
        self.name = name
        self.version = int(version)
        self.data_name = data_name
        self.input_shape = tuple(int(d) for d in input_shape)
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        #: compile-once warm-up manifest of a PREVIOUS load (fingerprint
        #: verification) and the entries THIS load's warm-up recorded
        #: (what the registry persists for the next one)
        self._warmup_manifest = warmup_manifest
        self.warmup_entries = []
        #: warm-up compile accounting for ``describe()`` (None when the
        #: persistent compile cache is disabled)
        self.warmup_cold_compiles = None
        self.warmup_cache_loads = None
        self._pred = _predict.Predictor(
            symbol_json, param_blob,
            {data_name: (self.buckets[-1],) + self.input_shape}, ctx=ctx)
        if self._pred._cache_cap < len(self.buckets):
            # 0 (caching disabled) is equally fatal here: every bucket
            # change would retrace — the exact storm buckets exist to stop
            raise MXNetError(
                "MXNET_PRED_CACHE_SIZE=%d holds fewer executors than the "
                "%d declared buckets of model %r: bucket round-robin "
                "would recompile every dispatch"
                % (self._pred._cache_cap, len(self.buckets), name))
        # the predictor is stateful (set_input/forward); one dispatch at
        # a time per model
        self._run_lock = threading.Lock()
        self.batcher = DynamicBatcher(
            self._dispatch, buckets=self.buckets,
            batch_timeout_us=batch_timeout_us,
            max_queue_depth=max_queue_depth, name=name,
            feature_shape=self.input_shape)
        self.warmup()
        if autostart:
            self.batcher.start()

    def warmup(self):
        """Compile every declared bucket now, at load time, so no live
        request ever eats a first-call XLA trace.

        With the compile-once subsystem active
        (``MXNET_COMPILE_CACHE_DIR``), the warm-up's compiles are
        persistent-cache loads on any repeat load of the same
        symbol+shapes — ``serving.warmup.cold_compiles`` reports how
        many executables actually paid a backend compile (0 on a warm
        reload); each bucket's build is recorded into
        :attr:`warmup_entries` and the lowered HLO is fingerprinted
        against the previous load's manifest, a mismatch being the
        cache-invalidation signal (the model's program changed)."""
        import time as _time

        with _warmup_record_lock:
            stats0 = _compile_cache.stats() if _compile_cache.enabled() \
                else None
            with _compile_cache.recording_scope() as rec:
                for b in self.buckets:
                    t0 = _time.perf_counter()
                    self._dispatch(np.zeros((b,) + self.input_shape,
                                            np.float32))
                    _telemetry.observe("serving.warmup.seconds",
                                       _time.perf_counter() - t0,
                                       model=self.name, bucket=b)
            self.warmup_entries = rec.entries
            stats1 = _compile_cache.stats() if stats0 is not None else None
        cold = warm = None
        if stats0 is not None:
            cold = stats1["misses"] - stats0["misses"]
            warm = stats1["hits"] - stats0["hits"]
            _telemetry.set_gauge("serving.warmup.cold_compiles", cold,
                                 model=self.name)
            _telemetry.set_gauge("serving.warmup.cache_loads", warm,
                                 model=self.name)
        self.warmup_cold_compiles = cold
        self.warmup_cache_loads = warm
        self._verify_warmup_fingerprints()
        _telemetry.event("serving.model.warm", model=self.name,
                         version=self.version, buckets=len(self.buckets),
                         cold_compiles=cold, cache_loads=warm)

    def _verify_warmup_fingerprints(self):
        """Compare this load's recorded builds against the previous
        load's warm-up manifest: same (kind, shape signature) lowering
        to different HLO means the model's compiled program changed —
        the invalidation signal operators watch on version swaps."""
        man = self._warmup_manifest
        if not man or not self.warmup_entries:
            return
        prev = {(e.get("kind_name"), e.get("shapes")): e.get("fingerprint")
                for e in man.get("entries", [])}
        for e in self.warmup_entries:
            old = prev.get((e.get("kind_name"), e.get("shapes")))
            new = e.get("fingerprint")
            if old and new and old != new:
                _telemetry.inc("compile_cache.manifest.fingerprint_changes")
                _telemetry.event("compile_cache.fingerprint_change",
                                 model=self.name, kind=e.get("kind_name"),
                                 shapes=e.get("shapes"), old=old, new=new)
                logging.warning(
                    "serving: model %r %s@%s compiles to different HLO "
                    "than the previous load (%s -> %s): the program "
                    "changed, warm-up paid a fresh compile", self.name,
                    e.get("kind_name"), e.get("shapes"), old, new)

    def _dispatch(self, rows):
        """One device dispatch: reshape to the row-count's bucket (an
        executor-cache hit after warm-up), forward, copy out."""
        with self._run_lock:
            shape = (int(rows.shape[0]),) + self.input_shape
            if self._pred._input_shapes[self.data_name] != shape:
                self._pred.reshape({self.data_name: shape})
            self._pred.set_input(self.data_name, rows)
            self._pred.forward()
            return self._pred.get_output(0)

    def pending_rows(self):
        """Rows queued or inside a device dispatch — the graceful-drain
        quiescence probe (uniform across servable kinds; pools expose
        the same method)."""
        return self.batcher.pending_rows()

    def describe(self):
        """Structured model card for ``GET /models`` and the per-model
        ``/healthz`` detail."""
        return {"name": self.name, "version": self.version,
                "kind": "predict", "buckets": list(self.buckets),
                "input_shape": list(self.input_shape),
                "data_name": self.data_name,
                "pending_rows": self.batcher.pending_rows(),
                "warmup": {"entries": len(self.warmup_entries),
                           "cold_compiles": self.warmup_cold_compiles,
                           "cache_loads": self.warmup_cache_loads}}

    def predict(self, data, deadline_ms=None,
                timeout=DynamicBatcher.DEFAULT_TIMEOUT):
        """Serve ``data`` through the batcher.  A single sample (ndim ==
        len(input_shape)) is auto-wrapped and unwrapped; a row batch goes
        through as-is."""
        data = np.asarray(data, np.float32)
        if data.ndim == len(self.input_shape):
            return self.batcher.predict(data[None], deadline_ms=deadline_ms,
                                        timeout=timeout)[0]
        return self.batcher.predict(data, deadline_ms=deadline_ms,
                                    timeout=timeout)

    def close(self, drain=True):
        """Permanent: drains (by default), then fails further submits
        fast — a straggler holding this version across a reload gets a
        typed error, not a hang."""
        self.batcher.close(drain=drain)
        self._pred.free()


class ModelRegistry:
    """``name -> ServedModel`` with atomic swap semantics."""

    def __init__(self, ctx=None, batch_timeout_us=2000,
                 max_queue_depth=128):
        self._ctx = ctx
        self._serve_opts = {"batch_timeout_us": batch_timeout_us,
                            "max_queue_depth": max_queue_depth}
        self._models = {}
        self._lock = threading.Lock()
        #: the attached :class:`~mxnet_tpu.serving.controller.
        #: FleetController` (None when the registry runs uncontrolled);
        #: the frontend's /fleet route and healthz block read it
        self.controller = None

    def attach_controller(self, controller):
        """Attach the fleet controller that manages this registry's
        decode pools (the controller's constructor calls this); the
        frontend resolves it through ``registry.controller``."""
        self.controller = controller
        return controller

    def load(self, name, symbol_json, param_blob, input_shape,
             data_name="data", buckets=(1, 8, 32), version=None,
             warmup_manifest=None):
        """Load (or reload) ``name``: build + warm the new
        :class:`ServedModel` off-registry, then swap atomically.  On any
        build failure the previously loaded version keeps serving.

        ``warmup_manifest`` (a :func:`mxnet_tpu.compile_cache.
        load_manifest` dict — :meth:`load_dir` wires it automatically)
        lets the warm-up verify each compiled bucket's HLO fingerprint
        against the previous load; a RELOAD with no manifest given
        verifies against the version it replaces."""
        prev = self.get(name, default=None)
        if version is None:
            version = 1 if prev is None else prev.version + 1
        if warmup_manifest is None and prev is not None \
                and prev.warmup_entries:
            warmup_manifest = {"entries": prev.warmup_entries}
        model = ServedModel(name, symbol_json, param_blob, input_shape,
                            data_name=data_name, buckets=buckets,
                            version=version, ctx=self._ctx,
                            warmup_manifest=warmup_manifest,
                            **self._serve_opts)
        with self._lock:
            prev = self._models.get(name)
            self._models[name] = model
        if prev is not None:
            prev.close()
        _telemetry.inc("serving.model.loads", model=name)
        _telemetry.event("serving.model.load", model=name, version=version)
        logging.info("serving: model %r v%d loaded (buckets %s)",
                     name, model.version, list(model.buckets))
        return model

    reload = load

    def register(self, name, servable, version=None):
        """Pointer-flip swap of an ALREADY-BUILT servable (a
        :class:`~mxnet_tpu.serving.pool.ReplicaPool`, a
        :class:`ServedModel` constructed off-registry, or anything
        exposing ``version``/``close``/``describe``): the caller builds
        and warms the new version outside the registry — replicas,
        engines, compiled programs, everything — then this swaps it in
        under the registry lock and drains the old one.  No request
        ever sees a half-swapped model; stragglers holding the old
        reference get its typed closed error, not a hang — and a decode
        POOL's in-flight generations MIGRATE onto the new servable
        (``close(successor=...)``: each straggler session re-admits by
        re-prefilling its transcript — bit-identical to an
        uninterrupted run when the versions share params, sampling from
        the new weights' logits otherwise) instead of being errored
        out."""
        if version is not None:
            servable.version = int(version)
        # healthz/models key by servable.name: the registration name is
        # authoritative (build the servable with the same name so its
        # telemetry labels agree — the stamp covers the mismatch case)
        servable.name = name
        with self._lock:
            prev = self._models.get(name)
            if version is None:
                # bare engines carry no version of their own: the
                # registry stamps one so every servable answers
                # .version uniformly
                servable.version = prev.version + 1 if prev is not None \
                    else int(getattr(servable, "version", 1))
            self._models[name] = servable
        if prev is not None:
            if hasattr(prev, "replicas") and (
                    hasattr(servable, "adopt")
                    or hasattr(servable, "resume")):
                # old decode pool -> new decode servable: migrate the
                # stragglers instead of draining/erroring them
                prev.close(successor=servable)
            else:
                prev.close()
        _telemetry.inc("serving.model.loads", model=name)
        _telemetry.event("serving.model.load", model=name,
                         version=servable.version)
        controller = self.controller
        if controller is not None:
            # a pointer flip replaced the pool object: the controller
            # must drop the old pool's autoscale/placement state and
            # adopt the successor on its next tick (best-effort — a
            # controller bug must not fail the swap)
            try:
                controller.on_register(name, servable)
            except Exception:  # noqa: broad-except
                logging.warning("serving: fleet controller on_register "
                                "hook failed for %r", name, exc_info=True)
        logging.info("serving: servable %r v%d registered (%s)",
                     name, servable.version,
                     type(servable).__name__)
        return servable

    @staticmethod
    def _read_manifest(model_dir):
        man_path = os.path.join(model_dir, MANIFEST)
        if not os.path.exists(man_path):
            raise MXNetError("no %s in %r: directory was never fully "
                             "published" % (MANIFEST, model_dir))
        with open(man_path) as f:
            return json.load(f)

    @staticmethod
    def _read_payload(model_dir, man):
        """Read + checksum every manifest-listed file ONCE (reloads are
        the fast path; hashing the in-memory bytes avoids a second pass
        over multi-GB params)."""
        blobs = {}
        for fname, digest in man.get("sha256", {}).items():
            path = os.path.join(model_dir, fname)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError as e:
                raise MXNetError(
                    "model file %r listed in the manifest is unreadable "
                    "(torn publish / partial copy?): %s" % (path, e))
            got = hashlib.sha256(blob).hexdigest()
            if got != digest:
                raise MXNetError(
                    "model file %r does not match its manifest checksum "
                    "(torn publish?): %s != %s" % (path, got, digest))
            blobs[fname] = blob
        return blobs

    def load_dir(self, model_dir, name=None, version=None):
        """Load/reload from a :func:`save_model` directory, verifying the
        manifest checksums first — a torn publish raises instead of
        swapping in half-written weights."""
        man = self._read_manifest(model_dir)
        for attempt in (0, 1):
            try:
                blobs = self._read_payload(model_dir, man)
                break
            except MXNetError:
                if attempt == 1:
                    raise
                # a concurrent publish may have GC'd the payloads THIS
                # manifest references; if the manifest moved on, retry
                # once against the newer publish — both were consistent
                new_man = self._read_manifest(model_dir)
                if new_man == man:
                    raise
                man = new_man
        symbol_json = blobs[man["symbol"]].decode()
        param_blob = blobs[man["params"]]
        wu_path = os.path.join(model_dir, WARMUP_MANIFEST)
        warmup_manifest = _compile_cache.load_manifest(wu_path)
        model = self.load(name or man["name"], symbol_json, param_blob,
                          man["input_shape"],
                          data_name=man.get("data_name", "data"),
                          buckets=man.get("buckets", (1, 8, 32)),
                          version=man["version"] if version is None
                          else version,
                          warmup_manifest=warmup_manifest)
        if _compile_cache.recording() and model.warmup_entries:
            # persist what THIS load compiled so the next load (version
            # swap, restart) replays it — atomic, never load-fatal
            try:
                _compile_cache.save_manifest(
                    wu_path, entries=model.warmup_entries,
                    model=model.name)
            except OSError as e:
                logging.warning(
                    "serving: could not write warm-up manifest %s: %s",
                    wu_path, e)
        return model

    def unload(self, name, drain=True):
        """Remove ``name`` and stop its batcher (draining by default)."""
        with self._lock:
            model = self._models.pop(name, None)
        if model is None:
            raise UnknownModel("model %r is not loaded" % name)
        model.close(drain=drain)
        _telemetry.event("serving.model.unload", model=name,
                         version=model.version)

    def get(self, name, **kw):
        with self._lock:
            model = self._models.get(name)
            loaded = sorted(self._models) if model is None else None
        if model is None:
            if "default" in kw:
                return kw["default"]
            raise UnknownModel("model %r is not loaded (have %s)"
                               % (name, loaded))
        return model

    def models(self):
        """Loaded models, sorted by name."""
        with self._lock:
            return sorted(self._models.values(), key=lambda m: m.name)

    def close(self):
        """Unload everything (server shutdown)."""
        with self._lock:
            models, self._models = list(self._models.values()), {}
        for m in models:
            m.close()
