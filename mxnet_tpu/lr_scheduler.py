"""Learning-rate schedules as pure functions of the update count.

API parity with the reference's ``python/mxnet/lr_scheduler.py``
(``LRScheduler`` / ``FactorScheduler`` / ``MultiFactorScheduler``, same
constructor kwargs and call contract), but the design is deliberately
different: the reference walks mutable state forward on every call
(``while num_update > count + step: base_lr *= factor``), which only
yields the right lr if the scheduler object replayed every update since
step 0.  Here each schedule is a *closed-form* function of
``num_update`` — ``lr(t) = base_lr * factor^decays(t)`` — so a
scheduler restored mid-training (checkpoint resume, ``num_update``
jumping from a loaded optimizer state) returns the correct lr on the
first call, and the same expression could be traced into a jitted
update step as a function of the step counter.

``base_lr`` stays the *undecayed* base (the optimizer assigns it after
construction); decay never mutates it.
"""

from __future__ import annotations

import bisect
import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler"]


class LRScheduler:
    """Base contract: ``sched(num_update) -> lr``.

    ``base_lr`` is written by the optimizer (``optimizer.py``: the
    ``learning_rate`` kwarg) after construction; subclasses treat it as
    the t=0 value and derive everything else from ``num_update``.
    """

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def _decays(self, num_update):
        """Number of decay events that have fired by ``num_update``."""
        raise NotImplementedError()

    def __call__(self, num_update):
        raise NotImplementedError()


class _GeometricDecay(LRScheduler):
    """Shared closed-form core: ``lr = base_lr * factor ** decays(t)``,
    floored at ``stop_lr``, with a transition log when the decay count
    changes between calls (observability parity with the reference's
    per-decay log lines, without the state machine)."""

    def __init__(self, factor, stop_lr=0.0):
        super().__init__()
        self.factor = factor
        self.stop_lr = stop_lr
        self._logged_decays = 0

    def __call__(self, num_update):
        k = self._decays(num_update)
        lr = self.base_lr * (self.factor ** k)
        floored = lr < self.stop_lr
        if floored:
            lr = self.stop_lr
        if k != self._logged_decays:
            self._logged_decays = k
            if floored:
                logging.info("Update[%d]: lr at lower bound %0.5e",
                             num_update, lr)
            else:
                logging.info("Update[%d]: Change learning rate to %0.5e",
                             num_update, lr)
        return lr


class FactorScheduler(_GeometricDecay):
    """Multiply the lr by ``factor`` every ``step`` updates.

    Reference ``lr_scheduler.py:36`` contract: the k-th decay fires once
    ``num_update`` exceeds ``k * step``, and the lr never drops below
    ``stop_factor_lr``.  Closed form: ``decays(t) = (t - 1) // step``.
    """

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1")
        super().__init__(factor, stop_lr=stop_factor_lr)
        self.step = step
        self.stop_factor_lr = stop_factor_lr

    def _decays(self, num_update):
        return max(0, num_update - 1) // self.step


class MultiFactorScheduler(_GeometricDecay):
    """Multiply the lr by ``factor`` at each boundary in ``step``.

    Reference ``lr_scheduler.py:77`` contract: boundary ``b`` has fired
    once ``num_update > b`` (strict).  Closed form: ``decays(t)`` is the
    number of boundaries strictly below ``num_update`` — a bisect over
    the sorted boundary list instead of a cursor walked by repeated
    calls.
    """

    def __init__(self, step, factor=1):
        if not isinstance(step, list) or len(step) < 1:
            raise ValueError("Schedule step must be a non-empty list")
        for prev, cur in zip(step, step[1:]):
            if cur <= prev:
                raise ValueError("Schedule step must be an increasing list")
        if step[0] < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        super().__init__(factor)
        self.step = step

    def _decays(self, num_update):
        # boundaries with b < num_update have fired (num_update > b)
        return bisect.bisect_left(self.step, num_update)
