"""Marshalling layer for the frontend C ABI (src/frontend_capi.cc).

The embedded interpreter inside ``libmxnet_tpu_frontend.so`` imports this
module once and drives the whole framework through these thin functions —
plain ints/strings/lists cross the C boundary, every object stays a
``PyObject*`` handle on the C side.  Keeping the marshalling here (rather
than in CPython C-API calls) keeps the C++ layer small and the behavior
identical to what a Python user gets.

Reference analog: ``src/c_api/c_api*.cc`` (2452 LoC of C++ glue over the
C++ runtime); here the runtime is the Python package itself, so the glue
is Python (SURVEY §2.7 row: C ABI is "the real public surface").
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import io as mxio
from . import ndarray as nd
from . import optimizer as opt
from . import symbol as sym
from .context import Context
from .kvstore import create as kv_create
from .ndarray import NDArray

_DTYPES = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
           4: np.int32, 6: "bfloat16"}
_DTYPE_CODES = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                "int32": 4, "bfloat16": 6}


def _ctx(dev_type, dev_id):
    # 1/3 = cpu (pinned alias), 2 = accelerator alias, 4 = tpu
    return Context("cpu" if dev_type in (1, 3) else "tpu", dev_id)


def _np_dtype(code):
    if code not in _DTYPES:
        raise ValueError("unknown dtype code %d" % code)
    d = _DTYPES[code]
    if d == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return d


def _host_view(addr, size, np_dtype):
    buf = (ctypes.c_char * (size * np.dtype(np_dtype).itemsize)) \
        .from_address(addr)
    return np.frombuffer(buf, dtype=np_dtype, count=size)


# ---- NDArray --------------------------------------------------------------

def nd_create(shape, dev_type, dev_id, dtype):
    return nd.zeros(tuple(shape), ctx=_ctx(dev_type, dev_id),
                    dtype=_np_dtype(dtype))


def nd_copy_from(a, addr, size):
    # host buffer is in the array's dtype unless bf16 (no numpy dtype on
    # the C side): bf16 arrays take f32 host data
    host_dt = np.float32 if str(a.dtype) == "bfloat16" else a.dtype
    v = _host_view(addr, size, host_dt).reshape(a.shape)
    a[:] = v


def nd_copy_to(a, addr, size):
    host_dt = np.float32 if str(a.dtype) == "bfloat16" else a.dtype
    out = _host_view(addr, size, host_dt)
    out[:] = np.asarray(a.asnumpy(), dtype=host_dt).reshape(-1)


def nd_shape(a):
    return tuple(int(d) for d in a.shape)


def nd_dtype(a):
    return _DTYPE_CODES.get(str(np.dtype(a.dtype).name)
                            if str(a.dtype) != "bfloat16" else "bfloat16",
                            0)


def nd_save(fname, arrays, keys):
    if keys is None:
        nd.save(fname, list(arrays))
    else:
        nd.save(fname, dict(zip(keys, arrays)))


def nd_load(fname):
    data = nd.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return keys, [data[k] for k in keys]
    return None, list(data)


def invoke(op_name, inputs, keys, vals):
    fn = getattr(nd, op_name)
    out = fn(*inputs, **dict(zip(keys, vals)))
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


def wait_all():
    nd.waitall()


def list_ops():
    from .ops.registry import list_ops as _lo

    return list(_lo())


def random_seed(seed):
    from . import random as _random

    _random.seed(seed)


# ---- Symbol ---------------------------------------------------------------

def sym_var(name):
    return sym.Variable(name)


def sym_op(op_name, name, pkeys, pvals, ikeys, inputs):
    kwargs = dict(zip(pkeys, pvals))
    if name:
        kwargs["name"] = name
    fn = getattr(sym, op_name)
    if ikeys is None:
        return fn(*inputs, **kwargs)
    kwargs.update(dict(zip(ikeys, inputs)))
    return fn(**kwargs)


def sym_group(syms):
    return sym.Group(list(syms))


def sym_list(s, which):
    if which == 0:
        return s.list_arguments()
    if which == 1:
        return s.list_auxiliary_states()
    return s.list_outputs()


def sym_json(s):
    return s.tojson()


def sym_from_json(js):
    return sym.load_json(js)


def sym_infer_shape(s, names, shapes):
    args, outs, auxs = s.infer_shape(**dict(zip(names, shapes)))
    fix = lambda ls: [tuple(int(d) for d in t) for t in (ls or [])]
    return fix(args), fix(outs), fix(auxs)


# ---- Executor -------------------------------------------------------------

def exec_simple_bind(s, dev_type, dev_id, names, shapes, grad_req):
    return s.simple_bind(_ctx(dev_type, dev_id), grad_req=grad_req,
                         **dict(zip(names, shapes)))


def exec_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def exec_backward(ex, head_grads):
    ex.backward(head_grads if head_grads else None)


def exec_outputs(ex):
    return list(ex.outputs)


def exec_get(ex, which, name):
    d = (ex.arg_dict, ex.grad_dict, ex.aux_dict)[which]
    return d.get(name)


# ---- Optimizer ------------------------------------------------------------

def opt_create(name, keys, vals):
    optimizer = opt.create(name, **dict(zip(keys, vals)))
    return opt.get_updater(optimizer)


def opt_update(updater, index, weight, grad):
    updater(index, grad, weight)


# ---- KVStore --------------------------------------------------------------

def kvstore_create(type_):
    return kv_create(type_)


def kv_init(kv, key, value):
    kv.init(key, value)


def kv_push(kv, key, value, priority):
    kv.push(key, value, priority=priority)


def kv_pull(kv, key, out, priority):
    kv.pull(key, out=out, priority=priority)


def kv_set_optimizer(kv, name, keys, vals):
    kv.set_optimizer(opt.create(name, **dict(zip(keys, vals))))


def kv_rank(kv):
    return int(kv.rank)


def kv_size(kv):
    return int(kv.num_workers)


def kv_barrier(kv):
    kv._barrier() if hasattr(kv, "_barrier") else None


def kv_close(kv):
    close = getattr(kv, "close", None)
    if close is not None:
        close()


# ---- DataIter -------------------------------------------------------------

class _IterState:
    """Iterator + its current batch (MXDataIterNext/GetData contract)."""

    def __init__(self, it):
        self.it = it
        self.batch = None

    def next(self):
        try:
            self.batch = next(self.it)
            return True
        except StopIteration:
            self.batch = None
            return False

    def before_first(self):
        self.it.reset()
        self.batch = None


def iter_create(name, keys, vals):
    fn = getattr(mxio, name)
    return _IterState(fn(**dict(zip(keys, vals))))


def iter_create_nd(data, label, batch_size, shuffle, last_batch_handle):
    return _IterState(mxio.NDArrayIter(
        data, label, batch_size=batch_size, shuffle=bool(shuffle),
        last_batch_handle=last_batch_handle))


def iter_next(st):
    return st.next()


def iter_before_first(st):
    st.before_first()


def iter_data(st):
    return st.batch.data[0]


def iter_label(st):
    return st.batch.label[0]


def iter_pad(st):
    return int(st.batch.pad or 0)
