"""Marshalling layer for the frontend C ABI (src/frontend_capi.cc).

The embedded interpreter inside ``libmxnet_tpu_frontend.so`` imports this
module once and drives the whole framework through these thin functions —
plain ints/strings/lists cross the C boundary, every object stays a
``PyObject*`` handle on the C side.  Keeping the marshalling here (rather
than in CPython C-API calls) keeps the C++ layer small and the behavior
identical to what a Python user gets.

Reference analog: ``src/c_api/c_api*.cc`` (2452 LoC of C++ glue over the
C++ runtime); here the runtime is the Python package itself, so the glue
is Python (SURVEY §2.7 row: C ABI is "the real public surface").
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import io as mxio
from . import ndarray as nd
from . import optimizer as opt
from . import symbol as sym
from .context import Context
from .kvstore import create as kv_create
from .ndarray import NDArray

_DTYPES = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
           4: np.int32, 6: "bfloat16"}
_DTYPE_CODES = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                "int32": 4, "bfloat16": 6}


def _ctx(dev_type, dev_id):
    # 1/3 = cpu (pinned alias), 2 = accelerator alias, 4 = tpu
    return Context("cpu" if dev_type in (1, 3) else "tpu", dev_id)


def _np_dtype(code):
    if code not in _DTYPES:
        raise ValueError("unknown dtype code %d" % code)
    d = _DTYPES[code]
    if d == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return d


def _host_view(addr, size, np_dtype):
    buf = (ctypes.c_char * (size * np.dtype(np_dtype).itemsize)) \
        .from_address(addr)
    return np.frombuffer(buf, dtype=np_dtype, count=size)


# ---- NDArray --------------------------------------------------------------

def nd_create(shape, dev_type, dev_id, dtype):
    return nd.zeros(tuple(shape), ctx=_ctx(dev_type, dev_id),
                    dtype=_np_dtype(dtype))


def nd_copy_from(a, addr, size):
    # host buffer is in the array's dtype unless bf16 (no numpy dtype on
    # the C side): bf16 arrays take f32 host data
    host_dt = np.float32 if str(a.dtype) == "bfloat16" else a.dtype
    v = _host_view(addr, size, host_dt).reshape(a.shape)
    a[:] = v


def nd_copy_to(a, addr, size):
    host_dt = np.float32 if str(a.dtype) == "bfloat16" else a.dtype
    out = _host_view(addr, size, host_dt)
    out[:] = np.asarray(a.asnumpy(), dtype=host_dt).reshape(-1)


def nd_shape(a):
    return tuple(int(d) for d in a.shape)


def nd_dtype(a):
    return _DTYPE_CODES.get(str(np.dtype(a.dtype).name)
                            if str(a.dtype) != "bfloat16" else "bfloat16",
                            0)


def nd_save(fname, arrays, keys):
    if keys is None:
        nd.save(fname, list(arrays))
    else:
        nd.save(fname, dict(zip(keys, arrays)))


def nd_load(fname):
    data = nd.load(fname)
    if isinstance(data, dict):
        keys = list(data.keys())
        return keys, [data[k] for k in keys]
    return None, list(data)


def nd_save_raw(arr):
    return nd.save_raw_bytes(arr)


def nd_load_raw(addr, size):
    return nd.load_from_raw_bytes(
        ctypes.string_at(ctypes.c_void_p(addr), size))


def rtc_create(name, input_names, output_names, kernel):
    from . import rtc

    return rtc.Rtc(name, list(input_names), list(output_names), kernel)


def rtc_push(r, ins, outs):
    r.push(list(ins), list(outs))


def invoke(op_name, inputs, keys, vals):
    fn = getattr(nd, op_name)
    out = fn(*inputs, **dict(zip(keys, vals)))
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


def wait_all():
    nd.waitall()


def list_ops():
    from .ops.registry import list_ops as _lo

    return list(_lo())


def get_version():
    from . import __version__ as v

    parts = (v.split(".") + ["0", "0"])[:3]
    nums = [int("".join(ch for ch in p if ch.isdigit()) or 0)
            for p in parts]
    return nums[0] * 10000 + nums[1] * 100 + nums[2]


def get_device_count(dev_type):
    if dev_type in (1, 3):
        import os

        return os.cpu_count() or 1
    from .context import num_tpus

    return num_tpus()


def list_data_iters():
    return [n for n in ("NDArrayIter", "CSVIter", "ImageRecordIter",
                        "ImageIter", "MNISTIter", "LibSVMIter",
                        "PrefetchingIter", "ResizeIter")
            if hasattr(mxio, n)]


# ---- profiler -------------------------------------------------------------

def profiler_set_config(mode, filename):
    from . import profiler

    profiler.profiler_set_config(mode="all" if mode else "symbolic",
                                 filename=filename)


def profiler_set_state(state):
    from . import profiler

    profiler.profiler_set_state("run" if state else "stop")


def profiler_dump():
    from . import profiler

    profiler.dump_profile()


def random_seed(seed):
    from . import random as _random

    _random.seed(seed)


def nd_slice(a, begin, end):
    return a[begin:end]


def nd_at(a, idx):
    return a[idx]


def nd_reshape(a, dims):
    return a.reshape(tuple(dims))


def nd_context(a):
    ctx = a.context
    return (1 if ctx.device_type == "cpu" else 4), int(ctx.device_id)


# ---- Symbol ---------------------------------------------------------------

def sym_var(name):
    return sym.Variable(name)


def sym_copy(s):
    """Deep graph clone (reference MXSymbolCopy): fresh nodes, shared
    OpDefs — so composing/attr-editing the copy cannot mutate graphs the
    original (or an executor bound to it) still references."""
    from .symbol import Symbol, _Node

    memo = {}
    for node in s._nodes():  # post-order: inputs are cloned before users
        memo[id(node)] = _Node(
            node.op, node.name, dict(node.attrs),
            [(memo[id(c)], ci) for c, ci in node.inputs],
            dict(node.misc_attr))
    return Symbol([(memo[id(n)], i) for n, i in s._outputs])


def sym_print(s):
    return s.debug_str() if hasattr(s, "debug_str") else repr(s)


def sym_get_attr(s, key):
    v = s.attr(key)
    return ("", 0) if v is None else (str(v), 1)


def sym_set_attr(s, key, value):
    s._set_attr(**{key: value})


def sym_list_attr(s, recursive):
    d = s.attr_dict() if recursive else (s.list_attr() or {})
    pairs = []
    if recursive:
        for node, attrs in sorted(d.items()):
            for k, v in sorted(attrs.items()):
                pairs += ["%s$%s" % (node, k), str(v)]
    else:
        for k, v in sorted(d.items()):
            pairs += [str(k), str(v)]
    return pairs


def sym_get_internals(s):
    return s.get_internals()


def sym_get_output(s, index):
    return s[int(index)]


def sym_compose(s, name, keys, args):
    """In-place compose (reference MXSymbolCompose): rewire variable
    inputs of every node in ``s`` to the given symbols' heads."""
    if keys is None:
        keys = s.list_arguments()[:len(args)]
    mapping = {}
    for k, a in zip(keys, args):
        mapping[k] = a._entry()
    # validate BEFORE mutating: a failing call must leave the graph
    # untouched (renaming needs a single-output head)
    head = s._entry()[0] if name else None
    for node in s._nodes():
        node.inputs = [
            mapping[child.name] if child.is_variable
            and child.name in mapping else (child, ci)
            for child, ci in node.inputs]
    if head is not None:
        head.name = name
    return None


def sym_infer_shape_partial(s, names, shapes):
    args, outs, auxs = s.infer_shape_partial(**dict(zip(names, shapes)))
    fix = lambda ls: [tuple(int(d) for d in t) if t is not None else ()
                      for t in (ls or [])]
    return fix(args), fix(outs), fix(auxs)


def sym_op(op_name, name, pkeys, pvals, ikeys, inputs):
    kwargs = dict(zip(pkeys, pvals))
    if name:
        kwargs["name"] = name
    fn = getattr(sym, op_name)
    if ikeys is None:
        return fn(*inputs, **kwargs)
    kwargs.update(dict(zip(ikeys, inputs)))
    return fn(**kwargs)


def sym_group(syms):
    return sym.Group(list(syms))


def sym_list(s, which):
    if which == 0:
        return s.list_arguments()
    if which == 1:
        return s.list_auxiliary_states()
    return s.list_outputs()


def sym_json(s):
    return s.tojson()


def sym_from_json(js):
    return sym.load_json(js)


def sym_infer_shape(s, names, shapes):
    args, outs, auxs = s.infer_shape(**dict(zip(names, shapes)))
    fix = lambda ls: [tuple(int(d) for d in t) for t in (ls or [])]
    return fix(args), fix(outs), fix(auxs)


# ---- Executor -------------------------------------------------------------

def exec_simple_bind(s, dev_type, dev_id, names, shapes, grad_req):
    return s.simple_bind(_ctx(dev_type, dev_id), grad_req=grad_req,
                         **dict(zip(names, shapes)))


def exec_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def exec_backward(ex, head_grads):
    ex.backward(head_grads if head_grads else None)


def exec_outputs(ex):
    return list(ex.outputs)


def exec_get(ex, which, name):
    d = (ex.arg_dict, ex.grad_dict, ex.aux_dict)[which]
    return d.get(name)


def exec_print(ex):
    lines = ["Executor (ctx=%s)" % (ex._ctx,)]
    for title, d in (("args", ex.arg_dict), ("aux", ex.aux_dict)):
        for n, a in d.items():
            lines.append("  %s %s: %s %s" % (title, n,
                                             tuple(a.shape), a.dtype))
    for i, o in enumerate(ex.outputs or []):
        lines.append("  output[%d]: %s %s" % (i, tuple(o.shape), o.dtype))
    return "\n".join(lines)


def exec_set_monitor(ex, cb_addr, data_addr):
    """Install a C monitor callback (MXFrontExecutorSetMonitorCallback):
    trampoline the (name, NDArrayHandle, user_data) C signature through
    ctypes.  ``id(arr)`` IS the PyObject* the C side treats as a handle;
    an owned reference is taken before the call, so the handle follows
    the same contract as every other NDArrayHandle in the ABI — the
    callback releases it with MXFrontNDArrayFree (and may keep it alive
    past the callback's return until then)."""
    if not cb_addr:
        ex.set_monitor_callback(None)
        return
    cfn = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_void_p)(cb_addr)
    user = ctypes.c_void_p(data_addr)

    def monitor(name, arr):
        ctypes.pythonapi.Py_IncRef(ctypes.py_object(arr))
        cfn(str(name).encode(), ctypes.c_void_p(id(arr)), user)

    ex.set_monitor_callback(monitor)


# ---- custom ops from C ----------------------------------------------------

_custom_keepalive = []  # registered trampolines live for the process


def custom_op_register(op_type, num_inputs, infer_addr, fwd_addr,
                       bwd_addr, user_addr):
    """Register a C-authored operator (MXFrontCustomOpRegister).

    The reference's ``MXCustomOpRegister`` hands C function pointers to
    its engine (``src/operator/custom/custom.cc:183``); here the
    pointers are wrapped with ctypes and staged into the traced graph
    with ``jax.pure_callback`` exactly like Python ``CustomOp``s
    (``ops/custom.py``) — so a C custom op works from imperative
    invoke, symbols, executors, and under jit.
    """
    import jax
    import jax.numpy as jnp

    from .ops.registry import register as _register

    u32p = ctypes.POINTER(ctypes.c_uint32)
    f32p = ctypes.POINTER(ctypes.c_float)
    INFER = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32, u32p,
                             ctypes.POINTER(u32p), u32p, u32p,
                             ctypes.c_void_p)
    FWD = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32,
                           ctypes.POINTER(f32p), ctypes.POINTER(ctypes.c_uint64),
                           f32p, ctypes.c_uint64, ctypes.c_void_p)
    BWD = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32,
                           ctypes.POINTER(f32p), f32p,
                           ctypes.POINTER(f32p),
                           ctypes.POINTER(ctypes.c_uint64),
                           ctypes.c_uint64, ctypes.c_void_p)
    infer = INFER(infer_addr)
    fwd = FWD(fwd_addr)
    bwd = BWD(bwd_addr) if bwd_addr else None
    user = ctypes.c_void_p(user_addr)
    _custom_keepalive.append((infer, fwd, bwd))
    n = int(num_inputs)

    def _out_shape(in_shapes):
        nds = (ctypes.c_uint32 * n)(*[len(s) for s in in_shapes])
        bufs = [(ctypes.c_uint32 * max(len(s), 1))(*s) for s in in_shapes]
        ptrs = (u32p * n)(*[ctypes.cast(b, u32p) for b in bufs])
        cap = 16
        out = (ctypes.c_uint32 * cap)()
        ndim = ctypes.c_uint32(cap)
        if infer(n, nds, ptrs, ctypes.byref(ndim), out, user) != 0:
            raise RuntimeError("%s: infer_shape callback failed" % op_type)
        return tuple(int(out[i]) for i in range(ndim.value))

    def _in_ptrs(arrs):
        ptrs = (f32p * n)(*[a.ctypes.data_as(f32p) for a in arrs])
        sizes = (ctypes.c_uint64 * n)(*[a.size for a in arrs])
        return ptrs, sizes

    def _fwd_host(oshape, *arrs):
        # oshape was fixed at trace time (_call_fwd); re-running the C
        # infer_shape callback here would add a per-step ctypes round
        # trip and could disagree with the traced result type
        arrs = [np.ascontiguousarray(np.asarray(a, np.float32))
                for a in arrs]
        outb = np.zeros(oshape, np.float32)
        ptrs, sizes = _in_ptrs(arrs)
        if fwd(n, ptrs, sizes, outb.ctypes.data_as(f32p), outb.size,
               user) != 0:
            raise RuntimeError("%s: forward callback failed" % op_type)
        return outb

    def _bwd_host(og, *arrs):
        arrs = [np.ascontiguousarray(np.asarray(a, np.float32))
                for a in arrs]
        og = np.ascontiguousarray(np.asarray(og, np.float32))
        grads = [np.zeros(a.shape, np.float32) for a in arrs]
        ptrs, sizes = _in_ptrs(arrs)
        gptrs = (f32p * n)(*[g.ctypes.data_as(f32p) for g in grads])
        if bwd(n, ptrs, og.ctypes.data_as(f32p), gptrs, sizes, og.size,
               user) != 0:
            raise RuntimeError("%s: backward callback failed" % op_type)
        return tuple(grads)

    def _call_fwd(xs):
        import functools

        oshape = _out_shape([tuple(map(int, x.shape)) for x in xs])
        res = jax.ShapeDtypeStruct(oshape, np.float32)
        return jax.pure_callback(functools.partial(_fwd_host, oshape),
                                 res, *[x.astype(jnp.float32) for x in xs])

    @jax.custom_vjp
    def op_fn(*xs):
        return _call_fwd(xs)

    def op_fwd(*xs):
        return _call_fwd(xs), xs

    if bwd is not None:
        def op_bwd(xs, og):
            res = tuple(jax.ShapeDtypeStruct(tuple(map(int, x.shape)),
                                             np.float32) for x in xs)
            gs = jax.pure_callback(
                _bwd_host, res, og.astype(jnp.float32),
                *[x.astype(jnp.float32) for x in xs])
            return tuple(g.astype(x.dtype) for g, x in zip(gs, xs))
    else:
        def op_bwd(xs, og):
            # header contract (c_frontend_api.h): gradient through a
            # backward-less C op is a TRACE-TIME error, not silent zeros
            raise RuntimeError(
                "%s: registered without a backward callback; gradient "
                "through it is undefined (MXFrontCustomOpRegister)"
                % op_type)

    op_fn.defvjp(op_fwd, op_bwd)

    def apply_fn(attrs, inputs, aux, is_train, rng):
        return [op_fn(*inputs)], None

    _register(op_type, apply_fn,
              arguments=tuple("data%d" % i for i in range(n)),
              hint=op_type.lower())


# ---- RecordIO -------------------------------------------------------------

def recio_open(uri, flag):
    from .recordio import MXRecordIO

    return MXRecordIO(uri, flag)


def recio_close(r):
    r.close()


def recio_write(r, addr, size):
    buf = ctypes.string_at(ctypes.c_void_p(addr), size)
    r.write(buf)


def recio_tell(r):
    return int(r.tell())


def recio_read(r):
    data = r.read()
    return data  # bytes or None at EOF


def recio_seek(r, pos):
    r.record.seek(int(pos))


# ---- Optimizer ------------------------------------------------------------

def opt_create(name, keys, vals):
    optimizer = opt.create(name, **dict(zip(keys, vals)))
    return opt.get_updater(optimizer)


def opt_update(updater, index, weight, grad):
    updater(index, grad, weight)


# ---- KVStore --------------------------------------------------------------

def kvstore_create(type_):
    return kv_create(type_)


def kv_init(kv, key, value):
    kv.init(key, value)


def kv_push(kv, key, value, priority):
    kv.push(key, value, priority=priority)


def kv_pull(kv, key, out, priority):
    kv.pull(key, out=out, priority=priority)


def kv_set_optimizer(kv, name, keys, vals):
    kv.set_optimizer(opt.create(name, **dict(zip(keys, vals))))


def kv_rank(kv):
    return int(kv.rank)


def kv_size(kv):
    return int(kv.num_workers)


def kv_barrier(kv):
    kv._barrier() if hasattr(kv, "_barrier") else None


def kv_close(kv):
    close = getattr(kv, "close", None)
    if close is not None:
        close()


# ---- DataIter -------------------------------------------------------------

class _IterState:
    """Iterator + its current batch (MXDataIterNext/GetData contract)."""

    def __init__(self, it):
        self.it = it
        self.batch = None

    def next(self):
        try:
            self.batch = next(self.it)
            return True
        except StopIteration:
            self.batch = None
            return False

    def before_first(self):
        self.it.reset()
        self.batch = None


def iter_create(name, keys, vals):
    fn = getattr(mxio, name)
    return _IterState(fn(**dict(zip(keys, vals))))


def iter_create_nd(data, label, batch_size, shuffle, last_batch_handle):
    return _IterState(mxio.NDArrayIter(
        data, label, batch_size=batch_size, shuffle=bool(shuffle),
        last_batch_handle=last_batch_handle))


def iter_next(st):
    return st.next()


def iter_before_first(st):
    st.before_first()


def iter_data(st):
    return st.batch.data[0]


def iter_label(st):
    return st.batch.label[0]


def iter_pad(st):
    return int(st.batch.pad or 0)
