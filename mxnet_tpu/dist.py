"""TPU-native multi-process data parallelism — the real ``dist_sync``.

The reference's ``dist_sync`` is engine-ordered ZPush/ZPull with server-side
merge (``src/kvstore/kvstore_dist.h:93-121``,
``kvstore_dist_server.h:164-227``): every gradient crosses the network to a
parameter server each step.  The TPU-native replacement (SURVEY §5.8) keeps
gradients on-chip: each worker process joins ONE ``jax.distributed`` process
group, the training step jits over the GLOBAL device mesh, and XLA inserts
the cross-process psum for the gradient reduction — ICI within a slice, DCN
across slices/hosts.  The parameter server survives only for
update-on-server semantics and explicit ``push``/``pull`` (KVStore API).

Wiring is pure env, like the reference (``DMLC_ROLE``, ``DMLC_WORKER_ID``,
``DMLC_NUM_WORKER``, ``DMLC_PS_ROOT_URI/PORT`` — SURVEY §3.3):
``tools/launch.py`` spawns workers with these set, and the coordinator
listens on ``DMLC_PS_ROOT_PORT + 1`` of the root host (override with
``MXNET_COORDINATOR_ADDRESS``).  ``MXNET_DIST_INGRAPH=0`` opts out, falling
back to pure parameter-server gradients.
"""

from __future__ import annotations

import os
import threading

import numpy as np

_lock = threading.Lock()
_state = {"initialized": False, "rank": 0, "num_processes": 1}


def is_initialized():
    return _state["initialized"]


def rank():
    return _state["rank"]


def num_processes():
    return _state["num_processes"]


def init_from_env(rank_hint=None):
    """Join the process group described by the launcher env.  Idempotent;
    returns True when this process is part of an initialized multi-process
    group.  No-ops (returns False) unless the env identifies this process
    as exactly one launcher-spawned worker — in-process multi-client
    setups (tests driving several KVStore clients from threads) must not
    grab a group identity."""
    with _lock:
        if _state["initialized"]:
            return True
        if os.environ.get("MXNET_DIST_INGRAPH", "1") == "0":
            return False
        from .elastic import enabled as _elastic_enabled

        if _elastic_enabled():
            # a jax.distributed process group freezes the world at
            # initialize(): membership cannot change without tearing the
            # whole group down.  Elastic jobs therefore keep gradients on
            # the PS plane, whose coordinator owns the membership epoch
            # (kvstore_server.py; docs/resilience.md "Elastic membership")
            return False
        # launcher-spawned workers carry an explicit role + worker count
        # (tools/launch.py); anything else (threaded multi-client tests,
        # plain scripts) must not grab a process-group identity
        if os.environ.get("DMLC_ROLE") != "worker" \
                or "DMLC_NUM_WORKER" not in os.environ:
            return False
        nw = int(os.environ["DMLC_NUM_WORKER"])
        pid = rank_hint if rank_hint is not None else \
            os.environ.get("DMLC_WORKER_ID")
        if nw < 2 or pid is None:
            return False
        pid = int(pid)
        coord = os.environ.get("MXNET_COORDINATOR_ADDRESS")
        if not coord:
            host = os.environ.get("DMLC_PS_ROOT_URI")
            port = os.environ.get("DMLC_PS_ROOT_PORT")
            if not host or not port:
                return False
            # first slot past the PS servers (server i binds port+i) —
            # only valid when rank 0 runs on the root host (single-host
            # env wiring); multi-host launches must set
            # MXNET_COORDINATOR_ADDRESS to rank-0's node
            nsrv = max(1, int(os.environ.get("DMLC_NUM_SERVER", "1")))
            coord = "%s:%d" % (host, int(port) + nsrv + 7)
        import jax

        timeout = int(os.environ.get("MXNET_DIST_INIT_TIMEOUT", "120"))
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nw, process_id=pid,
                                   initialization_timeout=timeout)
        _state.update(initialized=True, rank=pid, num_processes=nw)
        return True


def init(coordinator_address, num_processes_, process_id):
    """Explicit process-group init (the launcher-env-free path)."""
    with _lock:
        if _state["initialized"]:
            return
        import jax

        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes_,
                                   process_id=process_id)
        _state.update(initialized=True, rank=process_id,
                      num_processes=num_processes_)


def global_mesh(axis_name="data"):
    """1-D mesh over EVERY device in the process group."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis_name,))


def replicate(mesh, value):
    """Host value -> globally replicated array on the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    v = np.asarray(value)
    return jax.make_array_from_callback(
        v.shape, NamedSharding(mesh, P()), lambda idx: v[idx])


def shard_batch(mesh, local_value, axis_name="data"):
    """Per-process local batch -> global batch-sharded array."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis_name)), np.asarray(local_value))


def broadcast_from_root(value):
    """Rank-0's host value to every process (the reference's Init
    broadcast of rank-0 weights, ``kvstore_dist.h:58-76``)."""
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(np.asarray(value))


def local_rows(global_array):
    """This process's rows of a batch-sharded global array (sorted by
    global offset) — per-worker metric/outputs view."""
    shards = sorted(global_array.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def barrier(tag="mxnet_tpu_barrier"):
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)
