"""Profiler — per-op execution spans dumped as chrome://tracing JSON.

Reference: ``src/engine/profiler.{h,cc}`` (``Profiler`` singleton, per-device
``OprExecStat`` arrays, engine brackets every op with ``SetOprStart/End``,
``DumpProfile`` emits chrome://tracing JSON) + the Python veneer
``python/mxnet/profiler.py:10-38`` (``profiler_set_config`` /
``profiler_set_state`` / ``dump_profile``).

TPU-native: the "engine" is XLA/PJRT, so spans bracket (a) imperative op
dispatches (mode ``all``/``imperative``) and (b) executor fused forward/
backward computations (mode ``symbolic``) — the analog of the reference's
symbolic-ops-only default.  Device-side kernel timing comes from the XLA
profiler: ``profiler_set_config(trace_dir=...)`` additionally starts a
``jax.profiler`` trace viewable in TensorBoard/Perfetto, the analog of the
reference's chrome tracing of GPU worker threads.

Env: ``MXNET_PROFILER_AUTOSTART=1`` starts profiling at import
(``docs/how_to/env_var.md:64-67``).
"""

from __future__ import annotations

import json
import os
import threading
import time

from .base import MXNetError

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "State", "Mode", "now_us"]


class Mode:
    SYMBOLIC = "symbolic"
    IMPERATIVE = "imperative"
    ALL = "all"


class State:
    STOP = "stop"
    RUN = "run"


_lock = threading.Lock()
_state = State.STOP
_mode = Mode.SYMBOLIC
_filename = "profile.json"
_trace_dir = None
_events = []  # chrome trace event dicts
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def now_us():
    """Microseconds on the profiler clock — pair with :func:`record` to
    emit a span from code that brackets its own timing (the serving
    dispatch/request spans do)."""
    return _now_us()


def profiler_set_config(mode="symbolic", filename="profile.json",
                        trace_dir=None):
    """reference ``python/mxnet/profiler.py:10`` (``MXSetProfilerConfig``)."""
    global _mode, _filename, _trace_dir
    if mode not in (Mode.SYMBOLIC, Mode.IMPERATIVE, Mode.ALL):
        raise MXNetError("profiler mode must be symbolic/imperative/all")
    _mode = mode
    _filename = filename
    _trace_dir = trace_dir


def profiler_set_state(state="stop"):
    """reference ``python/mxnet/profiler.py:25`` (``MXSetProfilerState``).

    With a ``trace_dir`` configured, the jax profiler trace is started/
    stopped BEFORE ``_state`` commits: if ``start_trace``/``stop_trace``
    raises, the recorded state keeps describing reality (a failed start
    leaves the profiler stopped; a failed stop leaves it running so stop
    can be retried).  A second ``stop`` (or ``run``) is a no-op rather
    than an unmatched ``stop_trace`` call.
    """
    global _state
    if state not in (State.RUN, State.STOP):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    prev = _state
    if state == prev:
        return  # idempotent: nothing to transition, no trace calls
    if _trace_dir:
        import jax

        if state == State.RUN:
            jax.profiler.start_trace(_trace_dir)
        else:
            jax.profiler.stop_trace()
    _state = state


def running():
    return _state == State.RUN


def record(name, cat, start_us, end_us, tid=0):
    """Append one completed span (the ``OprExecStat`` analog)."""
    with _lock:
        _events.append({
            "name": name, "cat": cat, "ph": "X",
            "ts": start_us, "dur": end_us - start_us,
            "pid": 0, "tid": tid,
        })


class span:
    """Context manager bracketing one op execution (``SetOprStart/End``).

    When the profiler is stopped (the default), spans are no-ops: the
    enabled check happens once in ``__init__`` and nothing else is paid.
    When recording, callers must pass their jax result through ``sync()``
    so the duration covers real device execution, not just JAX's async
    dispatch (the engine analog syncs the CUDA stream before
    ``SetOprEnd`` — ``threaded_engine.h:296-307``).
    """

    __slots__ = ["name", "cat", "_t", "_on"]

    def __init__(self, name, cat):
        self._on = _state == State.RUN and (
            _mode == Mode.ALL
            or (_mode == Mode.SYMBOLIC and cat == "symbolic")
            or (_mode == Mode.IMPERATIVE and cat == "imperative"))
        if self._on:
            self.name = name
            self.cat = cat

    def __enter__(self):
        if self._on:
            self._t = _now_us()
        return self

    def sync(self, val):
        """Block until ``val``'s device work is done iff recording."""
        if self._on:
            import jax

            jax.block_until_ready(val)
        return val

    def __exit__(self, *exc):
        if self._on:
            record(self.name, self.cat, self._t, _now_us(),
                   tid=threading.get_ident() % 100000)
        return False


def dump_profile():
    """Write accumulated events as chrome://tracing JSON (reference
    ``Profiler::DumpProfile`` ``src/engine/profiler.cc:88``)."""
    with _lock:
        payload = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
        with open(_filename, "w") as f:
            json.dump(payload, f)
        _events.clear()
    return _filename


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":  # pragma: no cover
    profiler_set_state(State.RUN)
