"""Symbolic graph API (``mx.sym``).

Reference: nnvm Graph IR + ``python/mxnet/symbol.py`` (SURVEY §2.2/§2.6).

TPU-native design: a Symbol is a lightweight DAG of op nodes over the single
op registry.  There are no nnvm passes — the whole graph is *traced into one
XLA computation* at bind time (``executor.py``), so InferShape/InferType are
``jax.eval_shape`` over the trace, PlanMemory is XLA buffer assignment, and
the Gradient pass is ``jax.vjp``.  What remains here is exactly the graph
*construction* surface the reference exposes: composition via generated
``sym.<op>`` functions, ``Variable``/``Group``, ``list_arguments/
list_auxiliary_states/list_outputs``, ``infer_shape/infer_type``, attrs
(``AttrScope``, ctx_group, lr_mult), JSON save/load, and
``simple_bind``/``bind``.

Aux states (e.g. BatchNorm moving stats) are modelled as trailing inputs of
the op node, like nnvm does — auto-created as variables at composition time
(missing args likewise, matching ``sym.Convolution(data)`` auto-creating
``convolution0_weight``).
"""

from __future__ import annotations

import ast
import json
import sys

import numpy as np

from .attribute import AttrScope
from .base import MXNetError, NameManager
from .ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "pow",
           "maximum", "minimum"]


class _Node:
    __slots__ = ["op", "name", "attrs", "inputs", "misc_attr", "_id"]
    _counter = [0]

    def __init__(self, op, name, attrs, inputs, misc_attr=None):
        self.op = op  # OpDef or None for variables
        self.name = name
        self.attrs = attrs or {}
        self.inputs = inputs or []  # list of (node, out_index)
        self.misc_attr = dict(misc_attr or {})  # user attrs (ctx_group, ...)
        self._id = _Node._counter[0]
        _Node._counter[0] += 1

    @property
    def is_variable(self):
        return self.op is None

    def num_args(self):
        return len(self.op.list_arguments(self.attrs)) if self.op else 0


def _topo(nodes_out):
    """Post-order DFS over entry heads — nnvm IndexedGraph order."""
    order, seen = [], set()
    stack = [(n, False) for n, _ in reversed(nodes_out)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for child, _ in reversed(node.inputs):
            if id(child) not in seen:
                stack.append((child, False))
    return order


class Symbol:
    """A multi-output symbolic graph handle (reference ``symbol.py:52``)."""

    __slots__ = ["_outputs"]

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list of (node, out_index)

    # -- composition helpers ---------------------------------------------
    def _entry(self):
        if len(self._outputs) != 1:
            raise MXNetError("operation requires a single-output symbol; "
                             "use sym[i] to pick an output")
        return self._outputs[0]

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("no output named %r (have %s)" % (index, names))
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield self[i]

    @property
    def name(self):
        node, idx = self._outputs[0] if len(self._outputs) == 1 else (None, 0)
        return node.name if node is not None else None

    # -- introspection ----------------------------------------------------
    def _nodes(self):
        return _topo(self._outputs)

    def _arg_aux_vars(self):
        """Variables split into (args, auxs) by which op slot consumes them."""
        aux_ids = set()
        for node in self._nodes():
            if node.is_variable:
                continue
            na = node.num_args()
            for child, _ in node.inputs[na:]:
                aux_ids.add(id(child))
        args, auxs = [], []
        for node in self._nodes():
            if node.is_variable:
                (auxs if id(node) in aux_ids else args).append(node)
        return args, auxs

    def list_arguments(self):
        return [n.name for n in self._arg_aux_vars()[0]]

    def list_auxiliary_states(self):
        return [n.name for n in self._arg_aux_vars()[1]]

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
            else:
                outs = node.op.list_outputs(node.attrs)
                names.append("%s_%s" % (node.name, outs[idx]))
        return names

    def list_attr(self, recursive=False):
        if recursive:
            out = {}
            for node in self._nodes():
                for k, v in node.misc_attr.items():
                    out["%s_%s" % (node.name, k)] = v
            return out
        node, _ = self._entry()
        return dict(node.misc_attr)

    def attr(self, key):
        node, _ = self._entry()
        return node.misc_attr.get(key)

    def _set_attr(self, **kwargs):
        node, _ = self._entry()
        node.misc_attr.update({k: str(v) for k, v in kwargs.items()})

    def attr_dict(self):
        out = {}
        for node in self._nodes():
            d = dict(node.misc_attr)
            if not node.is_variable:
                d.update({k: _attr_str(v) for k, v in node.attrs.items()})
            if d:
                out[node.name] = d
        return out

    def get_internals(self):
        """All intermediate outputs as a Group (reference symbol.py
        get_internals, used for feature extraction / fine-tune)."""
        entries = []
        for node in self._nodes():
            if node.is_variable:
                entries.append((node, 0))
            else:
                for i in range(len(node.op.list_outputs(node.attrs))):
                    entries.append((node, i))
        return Symbol(entries)

    # -- shape / type inference ------------------------------------------
    def _infer_shapes_full(self, shape_kwargs, type_kwargs=None, partial=False):
        """Topological forward propagation with per-op backward filling.

        Returns dicts: var_shapes, var_dtypes, out_shapes, out_dtypes,
        entry->aval map.
        """
        import jax

        type_kwargs = type_kwargs or {}
        args, auxs = self._arg_aux_vars()
        var_shape = {}
        var_dtype = {}
        for n in args + auxs:
            s = shape_kwargs.get(n.name)
            if s is None and "__shape__" in n.misc_attr:
                s = ast.literal_eval(n.misc_attr["__shape__"])
            var_shape[n.name] = tuple(s) if s is not None else None
            var_dtype[n.name] = type_kwargs.get(n.name)
        entry_aval = {}

        def _known(nm):
            return var_shape.get(nm) is not None

        for node in self._nodes():
            if node.is_variable:
                if _known(node.name):
                    dt = var_dtype.get(node.name) or np.float32
                    entry_aval[(id(node), 0)] = jax.ShapeDtypeStruct(
                        var_shape[node.name], dt)
                continue
            op = node.op
            na = node.num_args()
            in_entries = node.inputs[:na]
            aux_entries = node.inputs[na:]
            in_shapes = []
            in_dtypes = []
            for child, ci in in_entries:
                av = entry_aval.get((id(child), ci))
                in_shapes.append(tuple(av.shape) if av is not None else None)
                in_dtypes.append(av.dtype if av is not None else None)
            aux_shapes = []
            for child, ci in aux_entries:
                av = entry_aval.get((id(child), ci))
                aux_shapes.append(tuple(av.shape) if av is not None else None)
            if op.infer_inputs is not None and (
                    any(s is None for s in in_shapes)
                    or any(s is None for s in aux_shapes)):
                in_shapes, aux_shapes = op.infer_inputs(
                    node.attrs, list(in_shapes), list(in_dtypes),
                    list(aux_shapes))
            # write back newly-filled variable shapes
            base_dt = next((d for d in in_dtypes if d is not None), None) \
                or np.float32
            for (child, ci), s in zip(in_entries, in_shapes):
                if s is not None and (id(child), ci) not in entry_aval \
                        and child.is_variable:
                    dt = var_dtype.get(child.name) or base_dt
                    var_shape[child.name] = tuple(s)
                    var_dtype[child.name] = dt
                    entry_aval[(id(child), ci)] = jax.ShapeDtypeStruct(
                        tuple(s), dt)
            for (child, ci), s in zip(aux_entries, aux_shapes):
                if s is not None and (id(child), ci) not in entry_aval \
                        and child.is_variable:
                    dt = var_dtype.get(child.name) or np.float32
                    var_shape[child.name] = tuple(s)
                    var_dtype[child.name] = dt
                    entry_aval[(id(child), ci)] = jax.ShapeDtypeStruct(
                        tuple(s), dt)
            ins = [entry_aval.get((id(c), ci)) for c, ci in in_entries]
            auxs_av = [entry_aval.get((id(c), ci)) for c, ci in aux_entries]
            if any(a is None for a in ins) or any(a is None for a in auxs_av):
                if partial:
                    continue
                missing = [c.name for (c, ci), a in
                           zip(node.inputs, ins + auxs_av) if a is None]
                raise MXNetError(
                    "infer_shape: cannot infer inputs %s of node %s"
                    % (missing, node.name))
            out_avals, _aux_up = op.infer(node.attrs, ins, auxs_av)
            for i, av in enumerate(out_avals):
                entry_aval[(id(node), i)] = av
        return var_shape, var_dtype, entry_aval

    def infer_shape(self, *args, **kwargs):
        """reference ``symbol.py`` infer_shape -> (arg_shapes, out_shapes,
        aux_shapes), each ordered like the respective list_*() call."""
        if args:
            kwargs = dict(zip(self.list_arguments(), args), **kwargs)
        shape_kwargs = {k: v for k, v in kwargs.items() if v is not None}
        var_shape, _vd, entry_aval = self._infer_shapes_full(shape_kwargs)
        arg_shapes = [var_shape.get(n) for n in self.list_arguments()]
        aux_shapes = [var_shape.get(n) for n in self.list_auxiliary_states()]
        out_shapes = [tuple(entry_aval[(id(n), i)].shape)
                      for n, i in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, **kwargs):
        var_shape, _vd, entry_aval = self._infer_shapes_full(kwargs, partial=True)
        arg_shapes = [var_shape.get(n) for n in self.list_arguments()]
        aux_shapes = [var_shape.get(n) for n in self.list_auxiliary_states()]
        out_shapes = [
            tuple(entry_aval[(id(n), i)].shape)
            if (id(n), i) in entry_aval else None
            for n, i in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, **kwargs):
        """Needs shapes too in this design; used with Module's type_dict."""
        raise MXNetError("infer_type: use infer_shape with type_dict via "
                         "simple_bind (dtype inference is joint on TPU)")

    # -- binding ----------------------------------------------------------
    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    shared_exec=None, group2ctx=None, **kwargs):
        from .executor import Executor

        return Executor._simple_bind(self, ctx, grad_req=grad_req,
                                     type_dict=type_dict,
                                     shared_exec=shared_exec,
                                     group2ctx=group2ctx, **kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor._bind(self, ctx, args, args_grad=args_grad,
                              grad_req=grad_req, aux_states=aux_states,
                              group2ctx=group2ctx, shared_exec=shared_exec)

    # -- eval convenience -------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from .context import current_context

        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # -- serialization ----------------------------------------------------
    def tojson(self):
        nodes = self._nodes()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "attrs": {k: _attr_str(v) for k, v in n.attrs.items()}
                if n.attrs else {},
                "misc_attrs": n.misc_attr,
                "inputs": [[nid[id(c)], ci] for c, ci in n.inputs],
            })
            if n.is_variable:
                jnodes[-1].pop("attrs")
        payload = {
            "nodes": jnodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_variable],
            "heads": [[nid[id(n)], i] for n, i in self._outputs],
            "mxnet_tpu_version": 1,
        }
        return json.dumps(payload, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __repr__(self):
        return "<Symbol %s>" % (self.name or self.list_outputs())

    # -- operators --------------------------------------------------------
    def __add__(self, other):
        return _sym_binop(self, other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return _sym_binop(self, other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _sym_binop(self, other, None, "_rminus_scalar")

    def __mul__(self, other):
        return _sym_binop(self, other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _sym_binop(self, other, "elemwise_div", "_div_scalar")

    __div__ = __truediv__

    def __rtruediv__(self, other):
        return _sym_binop(self, other, None, "_rdiv_scalar")

    __rdiv__ = __rtruediv__

    def __pow__(self, other):
        return _sym_binop(self, other, "_power", "_power_scalar")

    def __neg__(self):
        return _sym_binop(self, -1.0, None, "_mul_scalar")

    def __copy__(self):
        return Symbol(list(self._outputs))


def _attr_str(v):
    if isinstance(v, (tuple, list)):
        return str(tuple(v))
    return str(v)


def _sym_binop(lhs, rhs, arr_op, scalar_op):
    mod = sys.modules[__name__]
    if isinstance(rhs, Symbol):
        if arr_op is None:
            raise MXNetError("unsupported symbol-symbol op")
        return getattr(mod, arr_op)(lhs, rhs)
    return getattr(mod, scalar_op)(lhs, scalar=float(rhs))


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs):
    """reference ``symbol.py`` Variable"""
    misc = AttrScope.current().get(attr)
    if shape is not None:
        misc["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        misc["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        misc["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        misc["__dtype__"] = str(dtype)
    if init is not None:
        misc["__init__"] = init if isinstance(init, str) else init.dumps()
    misc.update({k: str(v) for k, v in kwargs.items()})
    return Symbol([(_Node(None, name, {}, [], misc), 0)])


var = Variable


def Group(symbols):
    """reference ``symbol.py`` Group — concat output lists."""
    entries = []
    for s in symbols:
        entries.extend(s._outputs)
    return Symbol(entries)


def _upgrade_legacy_json(payload):
    """Upgrade reference-MXNet symbol JSON in place (the
    ``src/nnvm/legacy_json_util.cc`` analog): 0.9.x nodes carry op params
    under ``"param"`` (or pre-NNVM ``"attr"``/``"attrs"`` mixing user
    attributes in) and a ``backward_source_id`` field.  Saved reference
    models load directly: ``mx.sym.load('ref-symbol.json')``."""
    for jn in payload["nodes"]:
        if "attrs" in jn and "misc_attrs" in jn:
            continue  # native format
        params = dict(jn.pop("param", {}) or {})
        misc = dict(jn.pop("attr", {}) or jn.pop("attrs", {}) or {})
        if jn["op"] != "null" and not params and misc:
            # very old format: op params and user attrs share one dict —
            # split by the op's declared param names
            op = _reg.get(jn["op"])
            params = {k: v for k, v in misc.items() if k in op.params}
            misc = {k: v for k, v in misc.items() if k not in op.params}
        jn["attrs"] = params
        jn["misc_attrs"] = misc
        jn.pop("backward_source_id", None)
    # 0.9.x heads may be [id, index, version]; keep the first two fields
    payload["heads"] = [h[:2] for h in payload["heads"]]
    return payload


def load_json(json_str):
    payload = json.loads(json_str)
    if "mxnet_tpu_version" not in payload:
        payload = _upgrade_legacy_json(payload)
    nodes = []
    for jn in payload["nodes"]:
        if jn["op"] == "null":
            nodes.append(_Node(None, jn["name"], {}, [],
                               jn.get("misc_attrs", {})))
        else:
            op = _reg.get(jn["op"])
            attrs = op.canonicalize_attrs(jn.get("attrs", {}))
            inputs = [(nodes[i], ci) for i, ci, *_ in jn["inputs"]]
            aux_names = op.list_aux_states(attrs)
            if aux_names and len(inputs) == len(op.list_arguments(attrs)):
                # reference 0.9.x JSON leaves aux states implicit (created
                # at bind); our graph threads them as trailing inputs —
                # synthesize the variables with the reference's names
                inputs = inputs + [
                    (_Node(None, "%s_%s" % (jn["name"], an), {}, []), 0)
                    for an in aux_names]
            nodes.append(_Node(op, jn["name"], attrs, inputs,
                               jn.get("misc_attrs", {})))
    return Symbol([(nodes[i], ci) for i, ci in payload["heads"]])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------------------
# generated sym.<op> composition functions (the _init_symbol_module analog,
# reference ``symbol.py:1244``)
# ---------------------------------------------------------------------------
def _compose(op, args, kwargs):
    name = kwargs.pop("name", None)
    attr = kwargs.pop("attr", None)
    sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
    attr_kwargs = {k: v for k, v in kwargs.items()
                   if not isinstance(v, Symbol)}
    pos = []
    for a in args:
        if not isinstance(a, Symbol):
            raise MXNetError("%s: positional args must be Symbols" % op.name)
        pos.append(a)
    if op.key_var_num_args and op.key_var_num_args not in attr_kwargs:
        attr_kwargs[op.key_var_num_args] = len(pos) + len(sym_kwargs)
    attrs = op.canonicalize_attrs(attr_kwargs)
    name = NameManager.current().get(name, op.hint)
    arg_names = op.list_arguments(attrs)
    aux_names = op.list_aux_states(attrs)

    inputs = []
    pi = iter(pos)
    for nm in arg_names:
        if nm in sym_kwargs:
            inputs.append(sym_kwargs.pop(nm)._entry())
        else:
            try:
                inputs.append(next(pi)._entry())
            except StopIteration:
                # auto-create variable (reference comp. semantics)
                inputs.append(Variable("%s_%s" % (name, nm))._outputs[0])
    for nm in aux_names:
        if nm in sym_kwargs:
            inputs.append(sym_kwargs.pop(nm)._entry())
        else:
            inputs.append(Variable("%s_%s" % (name, nm))._outputs[0])
    if sym_kwargs:
        raise MXNetError("%s: unknown symbol inputs %s"
                         % (op.name, sorted(sym_kwargs)))
    misc = AttrScope.current().get(attr)
    node = _Node(op, name, attrs, inputs, misc)
    return Symbol([(node, i)
                   for i in range(len(op.list_outputs(attrs)))]
                  if len(op.list_outputs(attrs)) > 1 else [(node, 0)])


def _make_sym_func(op_name):
    op = _reg.get(op_name)

    def fn(*args, **kwargs):
        return _compose(op, args, kwargs)

    fn.__name__ = op_name
    fn.__doc__ = op.doc or ("Symbolic op %r" % op_name)
    return fn


def _init_symbol_module():
    mod = sys.modules[__name__]
    for op_name in _reg.list_ops():
        if not hasattr(mod, op_name):
            setattr(mod, op_name, _make_sym_func(op_name))


_init_symbol_module()


def __getattr__(name):
    # late-registered ops resolve lazily (same contract as mx.nd)
    try:
        _reg.get(name)
    except MXNetError:
        raise AttributeError("module %r has no attribute %r"
                             % (__name__, name)) from None
    fn = _make_sym_func(name)
    setattr(sys.modules[__name__], name, fn)
    return fn


# aliases matching reference sym namespace; symbol∘scalar mixes dispatch
# to the *_scalar ops exactly like the reference's mx.sym.maximum et al.
def _sym_or_scalar(sym_op, scalar_op, rscalar_op=None):
    mod = sys.modules[__name__]

    def fn(lhs, rhs):
        if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
            return getattr(mod, sym_op)(lhs, rhs)
        if isinstance(lhs, Symbol):
            return getattr(mod, scalar_op)(lhs, scalar=float(rhs))
        if isinstance(rhs, Symbol):
            return getattr(mod, rscalar_op or scalar_op)(
                rhs, scalar=float(lhs))
        raise MXNetError("%s: at least one Symbol operand required"
                         % sym_op)

    fn.__name__ = sym_op.lstrip("_")
    return fn


pow = _sym_or_scalar("_power", "_power_scalar", "_rpower_scalar")  # noqa: A001
maximum = _sym_or_scalar("_maximum", "_maximum_scalar")
minimum = _sym_or_scalar("_minimum", "_minimum_scalar")
hypot = _sym_or_scalar("_hypot", "_hypot_scalar")
