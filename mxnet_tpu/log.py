"""Logging helpers (reference ``python/mxnet/log.py``): a leveled,
optionally-colored formatter and a ``get_logger`` convenience."""

from __future__ import annotations

import logging
import sys

__all__ = ["get_logger"]

_COLORS = {"WARNING": "\x1b[0;33m", "ERROR": "\x1b[0;31m",
           "CRITICAL": "\x1b[0;35m", "DEBUG": "\x1b[0;36m"}
_RESET = "\x1b[0m"


class _Formatter(logging.Formatter):
    """Reference-style single-letter level prefix, colored on ttys."""

    def __init__(self, colored):
        # static format string: record data (e.g. a logger name containing
        # '%') must never be interpolated into the format itself
        super().__init__("%(levelname).1s%(asctime)s %(name)s] %(message)s",
                         "%m%d %H:%M:%S")
        self._colored = colored

    def format(self, record):
        out = super().format(record)
        if self._colored and record.levelname in _COLORS:
            head, sep, tail = out.partition("] ")
            out = _COLORS[record.levelname] + head + _RESET + sep + tail
        return out


def get_logger(name=None, filename=None, filemode=None, level=logging.INFO):
    """Create/fetch a logger with the framework formatter attached
    (reference ``log.py`` getLogger)."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxnet_tpu_init", False):
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        colored = False
    else:
        handler = logging.StreamHandler(sys.stderr)
        colored = getattr(sys.stderr, "isatty", lambda: False)()
    handler.setFormatter(_Formatter(colored))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxnet_tpu_init = True
    return logger
