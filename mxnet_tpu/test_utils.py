"""Test harness utilities (reference ``python/mxnet/test_utils.py``, SURVEY §4).

``check_numeric_gradient`` (finite differences vs symbolic backward),
``check_symbolic_forward/backward`` (vs numpy reference), and
``check_consistency`` — the reference's CPU-vs-GPU parity harness becomes the
CPU-vs-TPU parity harness here, exactly the shape SURVEY §4.2 calls for.
"""

from __future__ import annotations

import numpy as np

from . import ndarray as nd
from .context import Context, cpu, current_context
from .ndarray import NDArray

__all__ = ["default_context", "assert_almost_equal", "almost_equal",
           "same", "rand_ndarray", "numeric_grad", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "rand_shape_nd"]


def default_context():
    return current_context()


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def same(a, b):
    return np.array_equal(a, b)


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return np.allclose(a, b, rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """reference ``test_utils.py:128``"""
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    if not almost_equal(a, b, rtol, atol):
        index = np.unravel_index(
            np.argmax(np.abs(a - b)), a.shape) if a.shape else ()
        rel = np.max(np.abs(a - b) / (np.abs(b) + 1e-12))
        raise AssertionError(
            "Items are not equal (rtol=%g):\n max rel err %g at %s: %s vs %s"
            % (rtol, rel, index, a[index] if a.shape else a,
               b[index] if b.shape else b))


def rand_ndarray(shape, ctx=None, scale=1.0):
    return nd.array(np.random.uniform(-scale, scale, shape)
                    .astype(np.float32), ctx=ctx)


def _as_exec_args(sym, location, ctx):
    arg_names = sym.list_arguments()
    if isinstance(location, dict):
        arrs = {k: (v if isinstance(v, NDArray)
                    else nd.array(np.asarray(v), ctx=ctx))
                for k, v in location.items()}
    else:
        arrs = {n: (v if isinstance(v, NDArray)
                    else nd.array(np.asarray(v), ctx=ctx))
                for n, v in zip(arg_names, location)}
    return arrs


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central finite differences through executor.forward (reference
    ``test_utils.py:260`` numeric_grad)."""
    grads = {}
    for name, arr in location.items():
        base = arr.asnumpy().astype(np.float64)
        g = np.zeros_like(base)
        flat = base.ravel()
        gflat = g.ravel()
        for i in range(flat.size):
            old = flat[i]
            flat[i] = old + eps
            executor.arg_dict[name][:] = base.reshape(arr.shape)
            out_p = executor.forward(is_train=use_forward_train)[0] \
                .asnumpy().astype(np.float64).sum()
            flat[i] = old - eps
            executor.arg_dict[name][:] = base.reshape(arr.shape)
            out_m = executor.forward(is_train=use_forward_train)[0] \
                .asnumpy().astype(np.float64).sum()
            gflat[i] = (out_p - out_m) / (2 * eps)
            flat[i] = old
            executor.arg_dict[name][:] = base.reshape(arr.shape)
        grads[name] = g
    return grads


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, ctx=None):
    """reference ``test_utils.py:360`` — finite differences vs backward."""
    ctx = ctx or default_context()
    location = _as_exec_args(sym, location, ctx)
    grad_nodes = grad_nodes or [n for n in sym.list_arguments()
                                if n in location]
    req = {n: ("write" if n in grad_nodes else "null")
           for n in sym.list_arguments()}
    executor = sym.bind(ctx, dict(location),
                        args_grad={n: nd.zeros(location[n].shape, ctx=ctx)
                                   for n in grad_nodes},
                        grad_req=req, aux_states=aux_states)
    executor.forward(is_train=True)
    executor.backward()
    sym_grads = {n: executor.grad_dict[n].asnumpy() for n in grad_nodes}
    num_grads = numeric_grad(
        executor, {n: location[n] for n in grad_nodes}, eps=numeric_eps)
    for name in grad_nodes:
        assert_almost_equal(num_grads[name], sym_grads[name], rtol=rtol,
                            atol=atol if atol is not None else rtol * 1e-1,
                            names=("numeric_%s" % name, "symbolic_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=1e-20,
                           aux_states=None, ctx=None):
    """reference ``test_utils.py:473``"""
    ctx = ctx or default_context()
    args = _as_exec_args(sym, location, ctx)
    executor = sym.bind(ctx, args, grad_req="null", aux_states=aux_states)
    outputs = executor.forward(is_train=False)
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, rtol=rtol, atol=atol)
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=1e-20, aux_states=None, grad_req="write",
                            ctx=None):
    """reference ``test_utils.py:527``"""
    ctx = ctx or default_context()
    args = _as_exec_args(sym, location, ctx)
    arg_names = sym.list_arguments()
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(arg_names, expected))
    grads = {n: nd.zeros(args[n].shape, ctx=ctx) for n in expected}
    req = {n: (grad_req if n in expected else "null") for n in arg_names}
    executor = sym.bind(ctx, args, args_grad=grads, grad_req=req,
                        aux_states=aux_states)
    executor.forward(is_train=True)
    executor.backward(
        [g if isinstance(g, NDArray) else nd.array(np.asarray(g), ctx=ctx)
         for g in out_grads] if out_grads is not None else None)
    for name, exp in expected.items():
        assert_almost_equal(executor.grad_dict[name], exp, rtol=rtol,
                            atol=atol)
    return executor.grad_dict


def check_consistency(sym, ctx_list, scale=1.0, rtol=1e-3, atol=1e-4,
                      arg_params=None):
    """reference ``test_utils.py:677`` — run the same symbol on every context
    (CPU vs TPU parity) and compare outputs + gradients."""
    shapes = ctx_list[0]
    del shapes
    exe_list = []
    for spec in ctx_list:
        spec = dict(spec)
        ctx = spec.pop("ctx")
        type_dict = spec.pop("type_dict", {})
        exe_list.append(sym.simple_bind(ctx, grad_req="write",
                                        type_dict=type_dict, **spec))
    arg0 = exe_list[0]
    np.random.seed(0)
    init = {}
    for name, arr in arg0.arg_dict.items():
        init[name] = np.random.normal(
            0, scale, arr.shape).astype(np.float32) if arg_params is None \
            or name not in arg_params else arg_params[name]
    outs = []
    grads = []
    for exe in exe_list:
        for name, arr in exe.arg_dict.items():
            arr[:] = init[name]
        exe.forward(is_train=True)
        exe.backward()
        outs.append([o.asnumpy() for o in exe.outputs])
        grads.append({n: g.asnumpy() for n, g in exe.grad_dict.items()})
    for other_out, other_grad in zip(outs[1:], grads[1:]):
        for a, b in zip(outs[0], other_out):
            assert_almost_equal(a, b, rtol=rtol, atol=atol)
        for name in grads[0]:
            assert_almost_equal(grads[0][name], other_grad[name], rtol=rtol,
                                atol=atol)
    return outs


def dump_op_coverage(note):
    """Write real op-invocation counts (``OpDef.apply`` calls this
    process) to ``$MXNET_OP_COVERAGE_OUT`` — shared by the tests/ and
    tests_tpu/ conftest ``pytest_sessionfinish`` hooks so the census
    invocation columns count executions, not word-grep mentions.
    A session that executed nothing (e.g. all tests skipped for lack of
    hardware) writes NOTHING rather than clobbering a previously
    recorded dump with empty counts."""
    import json
    import os
    import sys

    out = os.environ.get("MXNET_OP_COVERAGE_OUT")
    if not out:
        return
    from mxnet_tpu.ops import registry

    if not registry.INVOCATIONS:
        return
    payload = {
        "note": note,
        "argv": sys.argv[1:],
        "counts": dict(sorted(registry.INVOCATIONS.items())),
    }
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
