"""Training sentinel — hang watchdog, anomaly detection, supervision.

The stack survives crashes, SIGTERM preemptions, membership changes and
replica kills (docs/resilience.md), but until this module three failure
classes still defeated it:

* a **wedged step** — a dead peer mid-collective, a stuck recordio
  read, an XLA dispatch that never returns — hung the job forever with
  no diagnosis;
* **silent statistical divergence** — a loss/grad-norm spike that never
  goes non-finite — trained garbage the NaN guard cannot see;
* a **hard death** (kill -9, OOM) ended the job even though
  ``resume="auto"`` could continue it.

TensorFlow's design treats checkpoint/restore as the core
fault-tolerance primitive (Abadi et al., 2016); the checkpoints exist —
this module adds the *detection and supervision* that turns them into
actual availability:

:class:`Watchdog`
    A monitor thread fed by the telemetry phase hook
    (``telemetry.add_phase_hook``) tracks per-batch progress against a
    deadline auto-calibrated from the rolling median step time
    (``MXNET_STEP_DEADLINE_FACTOR`` x median, absolute floor
    ``MXNET_STEP_DEADLINE_MS``).  On expiry it dumps the flight
    recorder plus all-thread stacks, emits a ``reliability.hang``
    event, and — per ``MXNET_WATCHDOG_ACTION`` — injects a typed
    :class:`TrainingWedged` into the training thread (``raise``, the
    default), logs and re-arms (``warn``), or hard-exits the process
    with :data:`WEDGED_EXIT_CODE` for a supervisor to restart
    (``exit``, the escape hatch for hangs stuck inside a C call that
    an injected Python exception cannot unwind).  While armed it also
    maintains the heartbeat file ``MXNET_HEARTBEAT_FILE`` that
    :class:`Supervisor` watches.
:class:`AnomalyDetector`
    Rolling z-score over a scalar training statistic (fit feeds it the
    global gradient norm, ``executor.global_norm``): a spike beyond
    ``MXNET_ANOMALY_ZSCORE`` standard deviations of the
    ``MXNET_ANOMALY_WINDOW``-batch window trips ``fit``'s
    ``anomaly_policy`` — rollback-and-skip bounded by the consecutive
    ``MXNET_ROLLBACK_BUDGET`` — so a finite loss spike is handled the
    way a NaN is today.
:class:`Supervisor`
    Launches a training command, watches its exit code and the
    sentinel-written heartbeat file, and restarts it (the command
    resumes via ``resume="auto"``) with exponential backoff under
    ``MXNET_RESTART_BUDGET``; a crash loop exhausts the budget into a
    typed :class:`RestartBudgetExhausted` instead of thrashing.
    ``tools/supervise.py`` is the CLI face.

Cost model: everything here is OFF the hot loop.  A disabled watchdog
is zero work (``fit`` never constructs one); an enabled one costs a
timestamp store per timed phase on the phase-hook path and wakes its
monitor thread a few times per deadline — no device syncs either way.
The integrity-audit half of the sentinel lives where its collectives
do (:func:`mxnet_tpu.kvstore_mesh.build_replica_audit`); ``fit`` wires
both (docs/resilience.md "Watchdog, integrity audits & supervised
restarts").
"""

from __future__ import annotations

import json
import math
import os
import statistics
import subprocess
import sys
import threading
import time
import traceback

from . import perfdebug as _perfdebug
from . import telemetry as _telemetry
from .base import MXNetError
from .compile_cache import _env_float, _env_int

__all__ = ["TrainingWedged", "ReplicaDivergence", "AnomalyBudgetExhausted",
           "RestartBudgetExhausted", "WEDGED_EXIT_CODE", "Watchdog",
           "AnomalyDetector", "Supervisor", "FleetSupervisor",
           "watchdog_enabled", "thread_stacks", "dump_on_demand",
           "wedge_sleep", "note_progress"]

#: exit code of a watchdog hard-exit (``MXNET_WATCHDOG_ACTION=exit``):
#: distinct from Python's 1 and the shell's 126/127 so a supervisor can
#: tell "wedged, restart me" from "broken command line"
WEDGED_EXIT_CODE = 87


class TrainingWedged(MXNetError):
    """A training step exceeded the hang watchdog's deadline: the job
    was making no per-batch progress (dead collective peer, stuck read,
    dispatch that never returned).  The flight recorder + all-thread
    stacks were dumped before this was raised."""


class ReplicaDivergence(MXNetError):
    """A cross-replica integrity audit found replicated state whose bit
    patterns disagree across mesh replicas — silent divergence or
    corruption (a bad all-gather, a host/HBM bit-flip), never float
    noise: replicated arrays must agree exactly."""


class AnomalyBudgetExhausted(MXNetError):
    """``anomaly_policy`` tripped on more consecutive batches than the
    rollback budget allows — the spike is not transient; refusing to
    thrash rollback/skip forever."""


class RestartBudgetExhausted(MXNetError):
    """The supervisor's restart budget ran out: the command is crash-
    looping, not recovering.  Carries ``restarts`` and ``last_exit``."""

    def __init__(self, msg, restarts=0, last_exit=None):
        super().__init__(msg)
        self.restarts = restarts
        self.last_exit = last_exit


# -- knobs -------------------------------------------------------------------
def watchdog_enabled():
    """True when ``fit`` should arm the hang watchdog
    (``MXNET_WATCHDOG=1``)."""
    return os.environ.get("MXNET_WATCHDOG", "0") not in ("0", "", "false")


# -- stack dumps -------------------------------------------------------------
def thread_stacks():
    """Every live thread's current stack as ``{thread_name: [frames]}``
    — the "where is everyone stuck" half of a hang post-mortem (the
    flight recorder's ring is the "what was it doing before" half)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = "%s (%d)" % (names.get(tid, "unknown"), tid)
        out[label] = [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)]
    return out


def dump_on_demand(reason="sigquit", **fields):
    """Flight-recorder dump carrying all-thread stacks, without killing
    anything — what the fit-scope SIGQUIT handler calls (and the
    watchdog's trip path reuses).  Never raises; returns the dump path
    or None (disabled recorder / write failure)."""
    try:
        stacks = thread_stacks()
    except Exception:  # noqa: broad-except — diagnostics must not kill
        stacks = {}
    _telemetry.event("reliability.dump", reason=reason, **fields)
    return _perfdebug.flight_dump(reason, stacks=stacks, **fields)


def wedge_sleep():
    """The ``fit.wedge`` fault body: hold the training step wedged in
    20 ms slices — each slice boundary is a bytecode boundary, so the
    watchdog's injected :class:`TrainingWedged` lands promptly (one
    monolithic ``time.sleep`` would block the async exception until it
    returned).  Bounded by ``MXNET_WEDGE_FAULT_S`` (default 30) so an
    UNWATCHED run still terminates instead of trading a simulated hang
    for a real one."""
    limit = _env_float("MXNET_WEDGE_FAULT_S", 30.0)
    t0 = time.monotonic()
    while time.monotonic() - t0 < limit:
        time.sleep(0.02)


# -- hang watchdog -----------------------------------------------------------

#: watchdogs currently armed (normally 0 or 1) — module-level so
#: phase-free loops can tick liveness without holding a reference
_active_lock = threading.Lock()
_active_watchdogs = []


def note_progress():
    """Refresh every armed watchdog's progress clock — the liveness
    tick for work that emits no telemetry phases (the validation
    ``score()`` pass, epoch-end callbacks).  One truthiness check when
    no watchdog is armed."""
    if not _active_watchdogs:
        return
    with _active_lock:
        active = list(_active_watchdogs)
    for wd in active:
        wd.poke()


class Watchdog:
    """Per-batch-progress monitor for one ``fit`` call.

    Fed by the telemetry phase hook: every timed ``fit``-family phase
    exit refreshes the last-progress timestamp, and each ``data`` phase
    exit (the start-of-batch marker) closes the previous step's wall
    time into the rolling window the deadline is calibrated from —
    ``max(floor_ms, factor x median(step))``, so a model with 10 s
    steps and a model with 10 ms steps both get a deadline that means
    "many steps late", never "one slow step".

    ANY timed phase (any family) refreshes the progress clock — a
    serving or bulk phase proves the process is alive too — and loops
    that emit no phases at all (the validation ``score()`` pass,
    epoch-end wrap-up) tick it through :func:`note_progress`.  Until
    the first COMPLETED step, the deadline is 10x the floor: batch 0's
    trace+compile must not read as a hang (see :meth:`deadline_s`).

    The monitor thread wakes a few times per deadline, refreshes the
    heartbeat file, and on expiry runs the trip sequence: flight dump +
    stacks, ``reliability.hang``, then the configured action.  ``stop``
    (in fit's ``finally``) unhooks and joins — the thread never
    outlives its fit.
    """

    def __init__(self, action=None, factor=None, floor_ms=None,
                 heartbeat_path=None, logger=None):
        import logging

        self.logger = logger or logging
        self.action = action or os.environ.get(
            "MXNET_WATCHDOG_ACTION", "raise")
        if self.action not in ("raise", "warn", "exit"):
            raise MXNetError(
                "MXNET_WATCHDOG_ACTION must be raise/warn/exit, got %r"
                % (self.action,))
        self.factor = factor if factor is not None else _env_float(
            "MXNET_STEP_DEADLINE_FACTOR", 10.0)
        floor_ms = floor_ms if floor_ms is not None else _env_float(
            "MXNET_STEP_DEADLINE_MS", 30000.0)
        self.floor_s = max(0.01, floor_ms / 1000.0)
        self.heartbeat_path = heartbeat_path if heartbeat_path is not None \
            else (os.environ.get("MXNET_HEARTBEAT_FILE") or None)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._steps = []          # rolling step-time window (bounded)
        self._last_progress = None
        self._batch_t0 = None
        self._thread = None
        self._target_tid = None
        self._hook = None
        self.tripped = 0

    # -- feed (phase-hook thread = the training thread) -------------------
    def _on_phase(self, family, phase, seconds):
        now = time.monotonic()
        with self._lock:
            self._last_progress = now
            # only the fit loop's data-phase exits feed the step-time
            # calibration; every other phase is just proof of life
            if family == "fit" and phase == "data":
                if self._batch_t0 is not None:
                    self._steps.append(now - self._batch_t0)
                    if len(self._steps) > 64:
                        del self._steps[0]
                self._batch_t0 = now

    def poke(self):
        """Liveness tick for phase-free work (see
        :func:`note_progress`)."""
        with self._lock:
            self._last_progress = time.monotonic()

    def deadline_s(self):
        """Current deadline: ``factor x median(step)`` once ≥5 steps
        are observed, never below the floor — and 10x the floor until
        the FIRST COMPLETED step (startup grace).  The grace keys on a
        completed step, not on any phase: batch 0's fast ``data`` phase
        exits milliseconds in, while the trace+compile that must not
        read as a hang runs inside the subsequent ``forward_backward``
        phase — only the next ``data`` exit proves a whole step really
        finished."""
        with self._lock:
            steps = list(self._steps)
        if not steps:
            return self.floor_s * 10.0
        if len(steps) >= 5:
            return max(self.floor_s, self.factor * statistics.median(steps))
        # warm-up (1-4 steps): the MAX observed step carries the full
        # factor — a model whose steps are slower than the floor must
        # not be killed right after batch 1 just because the median
        # isn't trustworthy yet
        return max(self.floor_s, self.factor * max(steps))

    # -- lifecycle --------------------------------------------------------
    def start(self):
        """Arm: register the phase hook, remember the CALLING thread as
        the injection target, start the monitor.  Forces telemetry ON
        (the flight-recorder precedent): the phase hook IS the progress
        feed, and disabled telemetry never reaches hooks — an armed
        watchdog over dark telemetry would false-trip on a healthy
        job."""
        if self._thread is not None:
            return self
        _telemetry.enable()
        self._target_tid = threading.get_ident()
        with self._lock:
            self._last_progress = time.monotonic()
        self._hook = _telemetry.add_phase_hook(self._on_phase)
        with _active_lock:
            _active_watchdogs.append(self)
        self._thread = threading.Thread(target=self._monitor,
                                        name="sentinel-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Disarm: unhook, stop and join the monitor thread."""
        if self._thread is None:
            return
        with _active_lock:
            if self in _active_watchdogs:
                _active_watchdogs.remove(self)
        _telemetry.remove_phase_hook(self._hook)
        self._stop.set()
        self._thread.join(timeout=10)
        self._thread = None
        # final beat carries done=True: the supervisor must not treat
        # "fit finished, post-fit tail running" (final eval, export) as
        # a wedge just because the mtime froze — a later fit's fresh
        # beats overwrite the marker
        self._write_heartbeat(0.0, done=True)

    # -- monitor thread ---------------------------------------------------
    def _monitor(self):
        while True:
            deadline = self.deadline_s()
            interval = min(1.0, max(0.02, deadline / 8.0))
            if self._stop.wait(interval):
                return
            with self._lock:
                last = self._last_progress
            age = time.monotonic() - last
            self._write_heartbeat(age)
            if age > deadline:
                self._trip(age, deadline)
                if self._stop.wait(deadline):
                    # post-trip grace: give the injected exception (or
                    # the warn-only operator) a full deadline before
                    # re-tripping, so one hang is one dump, not a storm
                    return
                with self._lock:
                    self._last_progress = time.monotonic()

    def _write_heartbeat(self, age, done=False):
        if not self.heartbeat_path:
            return
        tmp = self.heartbeat_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"ts": round(time.time(), 3),
                           "pid": os.getpid(),
                           "progress_age_s": round(age, 3),
                           "done": done}, f)
            os.replace(tmp, self.heartbeat_path)
        except OSError as e:
            self.logger.debug("watchdog: heartbeat write failed: %s", e)

    def _trip(self, age, deadline):
        self.tripped += 1
        _telemetry.inc("reliability.hangs")
        _telemetry.event("reliability.hang", age_s=round(age, 3),
                         deadline_s=round(deadline, 3),
                         action=self.action)
        dump_on_demand("hang", age_s=round(age, 3),
                       deadline_s=round(deadline, 3))
        self.logger.error(
            "watchdog: no training progress for %.1fs (deadline %.1fs, "
            "%s median-calibrated) — %s", age, deadline,
            "floor" if deadline == self.floor_s else "step", self.action)
        if self.action == "exit":
            # for hangs wedged inside a C call: an injected Python
            # exception cannot unwind those — die with the wedged code
            # and let the supervisor restart from resume="auto"
            os._exit(WEDGED_EXIT_CODE)
        if self.action == "raise":
            self._inject(TrainingWedged)

    def _inject(self, exc_type):
        """Raise ``exc_type`` asynchronously in the training thread (the
        thread that called :meth:`start`).  Lands at the target's next
        bytecode boundary — a hang in pure-C land needs
        ``MXNET_WATCHDOG_ACTION=exit`` instead."""
        import ctypes

        res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(self._target_tid), ctypes.py_object(exc_type))
        if res > 1:  # pragma: no cover - interpreter-level failure
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(self._target_tid), None)
            self.logger.error("watchdog: async exception injection "
                              "failed (tid %s)", self._target_tid)


# -- statistical anomaly detection -------------------------------------------
class AnomalyDetector:
    """One-sided ROBUST rolling z-score over a scalar training
    statistic.

    ``observe(value)`` returns True when ``value`` spikes more than
    ``zscore`` robust standard deviations ABOVE the rolling window (a
    collapse toward zero is convergence, not divergence).  The scale is
    median/MAD, not mean/std: one outlier that slipped into the window
    (e.g. during warm-up) would inflate a stdev enough to hide every
    later spike behind it, while the median baseline shrugs it off.
    An anomalous value is NOT folded into the window — a spike must not
    poison the baseline it was judged against — and a non-finite value
    is always anomalous (belt and suspenders under
    ``nan_policy=None``).  The first ``min_samples`` observations only
    warm the window."""

    def __init__(self, window=None, zscore=None, min_samples=8):
        self.window = window if window is not None else _env_int(
            "MXNET_ANOMALY_WINDOW", 32)
        if self.window < min_samples:
            raise MXNetError(
                "anomaly window must be >= %d, got %d"
                % (min_samples, self.window))
        self.zscore = zscore if zscore is not None else _env_float(
            "MXNET_ANOMALY_ZSCORE", 6.0)
        self.min_samples = min_samples
        self._values = []

    def observe(self, value):
        value = float(value)
        if not math.isfinite(value):
            return True
        if len(self._values) >= self.min_samples:
            med = statistics.median(self._values)
            mad = statistics.median(abs(v - med) for v in self._values)
            # 1.4826: MAD -> stdev for a normal window.  Scale floor: a
            # converged, near-constant window (MAD ~ 0) must not turn
            # harmless jitter into 6-sigma events — the floor means a
            # trip always needs at least zscore x 5% headroom over the
            # median
            scale = max(1.4826 * mad, 0.05 * abs(med), 1e-12)
            if (value - med) / scale > self.zscore:
                return True
        self._values.append(value)
        if len(self._values) > self.window:
            del self._values[0]
        return False


# -- supervised auto-restart -------------------------------------------------
class Supervisor:
    """Launch-and-keep-alive harness for one training command.

    Runs ``cmd`` as a child process with ``MXNET_HEARTBEAT_FILE``
    pointed at ``heartbeat_path`` (the child's watchdog maintains it).
    Exit 0 ends supervision; ANY other death — nonzero exit, signal,
    the watchdog's :data:`WEDGED_EXIT_CODE`, or a live-but-heartbeat-
    stale child (killed hard, counted as wedged) — is restarted with
    exponential backoff, relying on the command's own
    ``resume="auto"`` to continue from its newest checkpoint.  More
    than ``budget`` restarts raises :class:`RestartBudgetExhausted`:
    a crash loop is a bug report, not a retry schedule.  The budget
    counts the CRASH LOOP, not the job's lifetime: a child that ran
    healthy for ``healthy_reset_s`` (default 300) before dying resets
    the counter — six preemptions across a week is availability
    working, six deaths in two minutes is the bug report.

    Heartbeat watching: a child that never writes a FRESH heartbeat
    (startup deadlock — hung import, stuck rendezvous) is killed once
    ``2 x heartbeat_timeout`` passes since launch (the 2x is startup
    allowance: import + fit arming happen before the watchdog's first
    write); after the first fresh write, plain ``heartbeat_timeout``
    staleness applies."""

    def __init__(self, cmd, budget=None, backoff_base=1.0,
                 backoff_max=60.0, heartbeat_path=None,
                 heartbeat_timeout=None, poll_s=0.2, logger=None,
                 resume_prefix=None, healthy_reset_s=300.0,
                 telemetry_dir=None, telemetry_proc=None):
        import logging

        self.cmd = list(cmd)
        #: telemetry export plumbing, mirroring the heartbeat file: the
        #: child gets MXNET_TELEMETRY_EXPORT_DIR/_PROC so its registry
        #: snapshots land where telemetry.aggregate()/graftop look
        self.telemetry_dir = telemetry_dir
        self.telemetry_proc = telemetry_proc
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
        #: checkpoint prefix for the pre-restart "where will resume
        #: land" log line (manifest-only probe; optional)
        self.resume_prefix = resume_prefix
        self.budget = budget if budget is not None else _env_int(
            "MXNET_RESTART_BUDGET", 5)
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.heartbeat_path = heartbeat_path
        self.heartbeat_timeout = heartbeat_timeout
        self.poll_s = poll_s
        self.logger = logger or logging
        self.healthy_reset_s = healthy_reset_s
        self.restarts = 0
        self._launched_at = None
        self._proc = None
        self._stopping = False

    def stop(self):
        """Stop supervising WITHOUT counting it as a crash: the child is
        terminated and :meth:`run` returns its exit code instead of
        restarting.  The fleet supervisor's shutdown path — a deliberate
        stop must never burn restart budget or wait out a backoff."""
        self._stopping = True
        self.terminate()

    def terminate(self):
        """Stop supervising AND stop the child: terminate (then kill)
        any live child process.  The CLI's interrupt path calls this so
        Ctrl-C on the supervisor never leaves an orphaned training run
        writing snapshots under the same prefix as a future
        relaunch."""
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()

    def _heartbeat_stale(self):
        if not self.heartbeat_path or not self.heartbeat_timeout:
            return False
        try:
            mtime = os.path.getmtime(self.heartbeat_path)
        except OSError:
            mtime = None  # never written at all
        fresh = mtime is not None and (
            self._launched_at is None or mtime >= self._launched_at)
        if not fresh:
            # no heartbeat from THIS incarnation yet (missing file, or
            # a leftover from the previous one): startup grace — but a
            # BOUNDED one, or a child wedged before arming its watchdog
            # (hung import, stuck rendezvous) would be polled forever
            return self._launched_at is not None and \
                time.time() - self._launched_at > 2 * self.heartbeat_timeout
        if time.time() - mtime <= self.heartbeat_timeout:
            return False
        # stale by mtime — but the watchdog's final beat marks a CLEAN
        # disarm (fit finished; the child is in its post-fit tail:
        # final eval, export).  Slow is not wedged; only read the
        # payload on this already-stale path
        try:
            if json.load(open(self.heartbeat_path)).get("done"):
                return False
        except (OSError, ValueError):
            pass  # torn/unreadable beat: treat as the stale it looks like
        return True

    def _run_once(self):
        """One child lifetime; returns its exit code (negative on
        signal), or :data:`WEDGED_EXIT_CODE` for a heartbeat-stale
        kill."""
        env = dict(os.environ)
        if self.heartbeat_path:
            env["MXNET_HEARTBEAT_FILE"] = self.heartbeat_path
        if self.telemetry_dir:
            env["MXNET_TELEMETRY_EXPORT_DIR"] = self.telemetry_dir
            if self.telemetry_proc:
                env["MXNET_TELEMETRY_EXPORT_PROC"] = self.telemetry_proc
        self._launched_at = time.time()
        proc = self._proc = subprocess.Popen(self.cmd, env=env)
        _telemetry.event("reliability.supervise.launch", pid=proc.pid,
                         restarts=self.restarts)
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if self._heartbeat_stale():
                self.logger.error(
                    "supervise: heartbeat %s stale beyond %.1fs — "
                    "killing wedged pid %d", self.heartbeat_path,
                    self.heartbeat_timeout, proc.pid)
                proc.kill()
                proc.wait()
                return WEDGED_EXIT_CODE
            time.sleep(self.poll_s)

    def run(self):
        """Supervise until the command succeeds (returns 0) or the
        restart budget is exhausted (raises
        :class:`RestartBudgetExhausted`)."""
        while True:
            rc = self._run_once()
            if rc == 0:
                _telemetry.event("reliability.supervise.done",
                                 restarts=self.restarts)
                return 0
            if self._stopping:
                return rc  # deliberate stop(), not a crash to restart
            uptime = time.time() - self._launched_at
            if self.restarts and self.healthy_reset_s \
                    and uptime >= self.healthy_reset_s:
                # the child ran healthy for a long stretch before this
                # death: not a crash loop — the budget guards against
                # thrash, not against a long job's lifetime misfortune
                self.logger.info(
                    "supervise: child was healthy for %.0fs — restart "
                    "budget reset", uptime)
                self.restarts = 0
            self.restarts += 1
            _telemetry.inc("reliability.restarts")
            _telemetry.event("reliability.supervise.restart",
                             exit_code=rc, restarts=self.restarts,
                             wedged=rc == WEDGED_EXIT_CODE)
            if self.restarts > self.budget:
                raise RestartBudgetExhausted(
                    "restart budget exhausted after %d restart(s); last "
                    "exit code %s — the command is crash-looping, not "
                    "recovering (fix the job; the newest checkpoint "
                    "under its prefix is intact)"
                    % (self.restarts - 1, rc),
                    restarts=self.restarts - 1, last_exit=rc)
            delay = min(self.backoff_max,
                        self.backoff_base * (2.0 ** (self.restarts - 1)))
            self.logger.warning(
                "supervise: command exited %s (%s); restart %d/%d in "
                "%.1fs (resume='auto' continues from the newest "
                "checkpoint)", rc,
                "wedged" if rc == WEDGED_EXIT_CODE else "crashed",
                self.restarts, self.budget, delay)
            if self.resume_prefix:
                from .checkpoint import latest_generation_summary

                gen = latest_generation_summary(self.resume_prefix)
                if gen is None:
                    self.logger.warning(
                        "supervise: no resumable generation under %r "
                        "yet — the restart begins from scratch",
                        self.resume_prefix)
                else:
                    self.logger.info(
                        "supervise: newest resumable generation: %s "
                        "epoch %d%s", gen["kind"], gen["epoch"],
                        "" if gen["nbatch"] is None
                        else " batch %d" % gen["nbatch"])
            # interruptible backoff: a fleet shutdown mid-backoff must
            # not wait out backoff_max before releasing the thread
            deadline = time.time() + delay
            while time.time() < deadline:
                if self._stopping:
                    return rc
                time.sleep(min(0.2, self.poll_s))


class FleetSupervisor:
    """:class:`Supervisor` generalized from one training child to a
    FLEET of processes: one Supervisor per command, each on its own
    thread, each with its OWN heartbeat file under ``heartbeat_dir``
    (``<name>.hb.json``) so two children can never confuse each
    other's liveness — the bug class ``tools/supervise.py
    --heartbeat-dir`` exists to close.

    Restart budget, backoff, and healthy-reset are PER CHILD (each
    wraps its own :class:`Supervisor`); a child that exhausts its
    budget is QUARANTINED — recorded, its thread released, the rest of
    the fleet supervised on — instead of taking the whole fleet down.
    :meth:`run` blocks until every child ends and returns 0 only when
    all of them exited 0 (quarantine counts as failure)."""

    def __init__(self, cmds, names=None, heartbeat_dir=None, budget=None,
                 backoff_base=1.0, backoff_max=60.0,
                 heartbeat_timeout=None, poll_s=0.2, logger=None,
                 healthy_reset_s=300.0, telemetry_dir=None):
        import logging

        cmds = [list(c) for c in cmds]
        if not cmds:
            raise MXNetError("FleetSupervisor needs >= 1 command")
        if names is None:
            names = ["child%d" % i for i in range(len(cmds))]
        if len(names) != len(set(names)) or len(names) != len(cmds):
            raise MXNetError("FleetSupervisor needs one unique name "
                             "per command")
        self.heartbeat_dir = heartbeat_dir
        if heartbeat_dir:
            os.makedirs(heartbeat_dir, exist_ok=True)
        self.telemetry_dir = telemetry_dir
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
        self.logger = logger or logging
        self._sups = {}
        for name, cmd in zip(names, cmds):
            hb = os.path.join(heartbeat_dir, "%s.hb.json" % name) \
                if heartbeat_dir else None
            self._sups[name] = Supervisor(
                cmd, budget=budget, backoff_base=backoff_base,
                backoff_max=backoff_max, heartbeat_path=hb,
                heartbeat_timeout=heartbeat_timeout, poll_s=poll_s,
                logger=self.logger, healthy_reset_s=healthy_reset_s,
                telemetry_dir=telemetry_dir,
                # the child NAME keys the merged view: graftop shows
                # trainer0/trainer1 rows, not two anonymous pids
                telemetry_proc=name if telemetry_dir else None)
        self._lock = threading.Lock()
        self._results = {}   # name -> exit code (75 for budget spent)
        self._threads = []

    def _run_child(self, name, sup):
        try:
            rc = sup.run()
        except RestartBudgetExhausted as e:
            self.logger.error("supervise[%s]: %s — QUARANTINED, the "
                              "rest of the fleet continues", name, e)
            _telemetry.event("reliability.supervise.quarantine",
                             child=name, restarts=e.restarts,
                             last_exit=e.last_exit)
            rc = 75  # EX_TEMPFAIL, the single-child CLI convention
        except Exception:  # noqa: broad-except — one child's
            # supervision bug must not strand the other threads'
            # join() in run()
            self.logger.exception("supervise[%s]: supervision failed",
                                  name)
            rc = 70  # EX_SOFTWARE
        with self._lock:
            self._results[name] = rc

    def run(self):
        """Supervise every child to completion; returns 0 iff all
        exited 0."""
        self._threads = [
            threading.Thread(target=self._run_child, args=(name, sup),
                             name="supervise-%s" % name, daemon=True)
            for name, sup in sorted(self._sups.items())]
        for t in self._threads:
            t.start()
        for t in self._threads:
            t.join()
        with self._lock:
            results = dict(self._results)
        failed = {n: rc for n, rc in results.items() if rc != 0}
        _telemetry.event("reliability.supervise.fleet_done",
                         children=len(self._sups), failed=len(failed))
        if failed:
            self.logger.error("supervise: fleet done, %d/%d child(ren) "
                              "failed: %s", len(failed), len(self._sups),
                              sorted(failed.items()))
            return 75 if 75 in failed.values() else \
                next(iter(sorted(failed.values())))
        self.logger.info("supervise: fleet of %d finished clean",
                         len(self._sups))
        return 0

    def results(self):
        """Per-child exit codes recorded so far (name -> rc)."""
        with self._lock:
            return dict(self._results)

    def terminate(self):
        """Stop the whole fleet: every child is stopped without
        restart (Ctrl-C path)."""
        for sup in self._sups.values():
            sup.stop()
