"""kvstore='mesh' — the GSPMD training plane (``mx.kv.create('mesh')``).

The reference exchanges gradients through a KVStore: per-key ``push``
(aggregate) + ``pull`` (redistribute), with the optimizer applied where
the weights live.  On a TPU mesh that whole plane dissolves into the
jitted train step (PAPER.md north star: ICI ``psum`` replacing
KVStore/NCCL allreduce): data/label shard over the mesh's batch axis,
parameters replicate, and XLA GSPMD compiles the gradient all-reduce
*into* the step — no host round-trips, no socket plane, no per-key RPC.
:class:`KVStoreMesh` is the KVStore-interface face of that plane:
``fit(kvstore='mesh')`` selects it, ``Module.init_optimizer`` adopts its
mesh (re-binding the executor arrays as global jax Arrays), and from
then on the PR 4 fused ``train_sgd``/``train_guard`` executor kinds run
the whole dp step as one XLA program.

ZeRO-style weight-update sharding (Xu et al., "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training"): for eligible
parameters the update itself is sharded over the batch axis —

* the batch-summed gradient is CONSUMED row-sharded, so the GSPMD
  partitioner lowers the would-be all-reduce to a **reduce-scatter**;
* each device owns its row slice of the optimizer state (momentum) and
  computes only its slice of the update — per-device optimizer-state
  HBM drops ~world-size (``optimizer_state_hbm`` pins it);
* the updated rows **all-gather** back into the replicated parameter.

The sharded update runs under :func:`~jax.experimental.shard_map` with
the collectives spelled explicitly (``all_gather`` / ``psum`` over the
named batch axis), so graftlint's ``collective-consistency`` pass can
prove the axis vocabulary and CI's seeded-mutation test can verify a
swapped axis name is caught.

Snapshots shard with the update plane: see
``checkpoint.write_snapshot`` (per-shard payload files + a stitching
manifest keyed by :func:`mxnet_tpu.elastic.assign_keys`) and
docs/how_to/multi_devices.md "Sharded fit".
"""

from __future__ import annotations

import os

import numpy as np

from .base import MXNetError
from .kvstore import KVStore, _ctype_key_value

__all__ = ["KVStoreMesh", "default_mesh", "zero_sgd_update",
           "zero_eligible_names", "optimizer_state_hbm",
           "build_replica_audit", "DATA_AXIS"]

#: the mesh axis that shards the batch (and the ZeRO update rows)
DATA_AXIS = "data"


def default_mesh():
    """The process-default device plane: a 1-axis ``('data',)`` mesh over
    ``MXNET_MESH_DEVICES`` jax devices (default: all of them)."""
    from .parallel.mesh import make_mesh

    n = os.environ.get("MXNET_MESH_DEVICES")
    n = int(n) if n else None
    return make_mesh(n_devices=n, axis_names=(DATA_AXIS,))


class KVStoreMesh(KVStore):
    """The KVStore interface as a *device plane* over a jax Mesh.

    There is no server and no transport: ``init`` registers the live
    parameter array (mesh-placed by the owning Module), ``push`` sums
    the pushed device list and applies the updater on the stored value
    (the reference's update-where-the-weights-live semantics), ``pull``
    copies the stored value out.  During ``fit`` none of that runs per
    step — ``in_graph_sync`` tells Module the gradient plane is already
    inside the jitted step, so ``update()`` bypasses the store entirely
    and the per-step collective traffic is exactly the in-graph
    ``psum``/reduce-scatter/all-gather GSPMD compiled (pinned by
    tests/test_mesh_kvstore.py: zero kvstore push/pull per step)."""

    #: Module keys mesh adoption / ZeRO / sharded snapshots off this
    is_mesh = True
    #: gradients reduce in-graph; the updater runs locally on every
    #: device (same update everywhere — there is no server optimizer)
    in_graph_sync = True

    def __init__(self, mesh=None):
        super().__init__("mesh")
        self.mesh = mesh if mesh is not None else default_mesh()
        names = self.mesh.axis_names
        self.axis = DATA_AXIS if DATA_AXIS in names else names[0]

    @property
    def world(self):
        """Devices on the batch axis — the gradient-reduction fan-in."""
        return int(self.mesh.shape[self.axis])

    @property
    def num_workers(self):
        # single-process plane: Module already binds the GLOBAL batch,
        # so rescale_grad must NOT be scaled by the device count
        return 1

    # -- data plane (API parity; fit never routes gradients here) --------
    def init(self, key, value):
        """Like the base store, a duplicate key is an error; the stored
        value is a live REFERENCE to the bound (mesh-placed) array, not
        a copy — the mesh store IS the training state, so ``pull``
        observes training progress exactly like the reference's
        update-on-kvstore pull."""
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            self._store[k] = vlist[0]

    # push/pull/save_optimizer_states inherit the base local semantics,
    # applied to the live references: push device-merges and runs the
    # updater (or assigns) on the stored value, and the optimizer
    # states may hold mesh-sharded device arrays — pickling gathers
    # each to one full host buffer, so the written bytes match a
    # single-device run's


# -- ZeRO update math --------------------------------------------------------

def zero_eligible_names(names, shapes, world, min_elems=None):
    """The subset of ``names`` whose update can shard over ``world``
    devices: leading dim divisible by the world size, and at least
    ``MXNET_MESH_ZERO_MIN_ELEMS`` elements (sharding tiny biases buys
    nothing and costs an all-gather each)."""
    if world <= 1:
        return ()
    if min_elems is None:
        min_elems = int(os.environ.get(
            "MXNET_MESH_ZERO_MIN_ELEMS", "1024") or 1024)
    out = []
    for n in names:
        shp = shapes[n]
        if shp and shp[0] % world == 0 \
                and int(np.prod(shp)) >= min_elems:
            out.append(n)
    return tuple(out)


def zero_sgd_update(mesh, momentum, rescale_grad, clip_gradient,
                    guard=False, axis_name=DATA_AXIS):
    """Build the ZeRO-sharded SGD(-momentum) step for ONE parameter.

    Returns ``apply(p, g, m, lr, wd) -> (new_p, new_m, flag)`` (``new_m``
    / ``flag`` are None when momentum == 0 / ``guard`` is False).  The
    body runs under ``shard_map`` over ``axis_name``:

    * ``p`` enters row-sharded (a local slice of the replicated param);
    * ``g`` enters row-sharded — the batch-summed gradient consumed at
      ``P(axis)`` is lowered by the partitioner to a reduce-scatter
      instead of the all-reduce the unsharded update would need;
    * ``m`` (the persistent optimizer-state rows) enters and leaves
      row-sharded — each device stores only its 1/world slice;
    * the updated rows ``all_gather`` back into the full parameter, and
      under ``guard`` the per-shard non-finite flag ``psum``s into the
      global batch flag.

    The per-row math is :func:`~mxnet_tpu.executor.sgd_step_math` — the
    same function the unsharded fused step uses, so a 1-device mesh is
    bit-identical to plain ``fit`` by construction.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from .executor import sgd_step_math

    has_mom = momentum != 0.0

    def _shard_math(p, g, m, lr, wd):
        new_p_shard, new_m = sgd_step_math(
            p, g, m, lr, wd, momentum, rescale_grad, clip_gradient)
        new_p = jax.lax.all_gather(new_p_shard, axis_name, axis=0,
                                   tiled=True)
        flag = None
        if guard:
            bad = jnp.logical_not(jnp.all(jnp.isfinite(g)))
            flag = jax.lax.psum(bad.astype(jnp.int32), axis_name) > 0
        return new_p, new_m, flag

    if has_mom:
        def body(p, g, m, lr, wd):
            new_p, new_m, flag = _shard_math(p, g, m, lr, wd)
            return (new_p, new_m, flag) if guard else (new_p, new_m)

        in_specs = (P(axis_name), P(axis_name), P(axis_name), P(), P())
        out_specs = (P(), P(axis_name), P()) if guard \
            else (P(), P(axis_name))
    else:
        def body(p, g, lr, wd):
            new_p, _m, flag = _shard_math(p, g, None, lr, wd)
            return (new_p, flag) if guard else (new_p,)

        in_specs = (P(axis_name), P(axis_name), P(), P())
        out_specs = (P(), P()) if guard else (P(),)

    # check_rep=False: the replicated outputs are established by the
    # explicit all_gather/psum above, which this jax version's static
    # replication checker cannot see through
    sm = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)

    def apply(p, g, m, lr, wd):
        res = sm(p, g, m, lr, wd) if has_mom else sm(p, g, lr, wd)
        if has_mom:
            return res if guard else (res[0], res[1], None)
        return (res[0], None, res[1]) if guard else (res[0], None, None)

    return apply


def mesh_param_step(mesh, momentum, rescale_grad, clip_gradient,
                    zero_names, guard=False, axis_name=DATA_AXIS):
    """Per-parameter update dispatch shared by BOTH mesh fused-step
    builders (executor ``train_sgd_mesh`` and Module's two-dispatch
    fused update), so their numerics and layout pinning can never
    diverge.  Returns ``step(name, p, g, m, lr, wd) -> (new_p,
    new_m_or_None, flag_or_None)``: ZeRO-eligible params route through
    :func:`zero_sgd_update`, the rest through plain ``sgd_step_math``;
    every output is pinned with ``with_sharding_constraint`` (params
    replicated, ZeRO momentum row-sharded) — an unconstrained output
    lets the partitioner pick a fresh layout each build and the stored
    arrays drift."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .executor import sgd_step_math

    zero_set = frozenset(zero_names)
    zupd = zero_sgd_update(mesh, momentum, rescale_grad, clip_gradient,
                           guard=guard, axis_name=axis_name)
    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P(axis_name))

    def step(name, p, g, m, lr, wd):
        if name in zero_set:
            new_p, new_m, flag = zupd(p, g, m, lr, wd)
        else:
            new_p, new_m = sgd_step_math(p, g, m, lr, wd, momentum,
                                         rescale_grad, clip_gradient)
            flag = None
        new_p = jax.lax.with_sharding_constraint(new_p, rep)
        if new_m is not None:
            new_m = jax.lax.with_sharding_constraint(
                new_m, row if name in zero_set else rep)
        return new_p, new_m, flag

    return step


# -- cross-replica integrity audit -------------------------------------------

def _bit_checksum(x):
    """uint32 wraparound sum of ``x``'s BIT PATTERN — not a float sum:
    two replicas that differ by one flipped mantissa/exponent/sign bit
    (or by a denormal/NaN payload a float compare would launder) always
    produce different checksums, and -0.0 vs +0.0 — numerically equal,
    bit-distinct — is flagged as the divergence it is.  Traced inside
    the audit program; 8-byte dtypes bitcast to a (..., 2) uint32 view
    (no uint64 dependence — jax's default x64-disabled mode would
    silently truncate it)."""
    import jax
    import jax.numpy as jnp

    if x.dtype == jnp.bool_:
        u = x.astype(jnp.uint8)
    elif jnp.issubdtype(x.dtype, jnp.integer) and x.dtype.itemsize <= 4:
        u = x
    else:
        width = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32,
                 8: jnp.uint32}[x.dtype.itemsize]
        u = jax.lax.bitcast_convert_type(x, width)
    return jnp.sum(u.astype(jnp.uint32))


def build_replica_audit(mesh, axis_name=DATA_AXIS):
    """ONE jitted program that verifies replica integrity in-graph.

    Returns ``audit(arrays) -> jax array [mismatch_count, first_bad]``:
    per mesh replica (shard along ``axis_name``), fold every input
    array to its :func:`_bit_checksum`, ``all_gather`` the per-replica
    checksum vectors over the axis, and count the arrays whose
    checksums do NOT agree bit-exactly across replicas.  Replicated
    params/aux MUST agree exactly — the cross-replica weight-update
    sharding plane (Xu et al.) re-establishes replication every step
    (ZeRO rows re-enter the replicated param through the update's
    all-gather, which is how "ZeRO-owned rows checked post-gather"
    falls out of auditing the params themselves) — so any difference
    is silent divergence or corruption, not numerics.  The caller does
    one small host read of the returned pair; everything else stays on
    device (docs/resilience.md "Cross-replica integrity audits").

    The per-replica view comes from ``shard_map`` with replicated
    in-specs: each device contributes ITS OWN copy of every replicated
    buffer, which is exactly what a bit-flip on one replica corrupts.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def body(arrays):
        local = jnp.stack([_bit_checksum(a) for a in arrays])
        every = jax.lax.all_gather(local, axis_name)   # (world, n)
        bad = jnp.any(every != every[0:1], axis=0)     # (n,)
        count = jnp.sum(bad.astype(jnp.int32))
        first = jnp.argmax(bad).astype(jnp.int32)      # 0 when clean
        return jnp.stack([count, first])

    # check_rep=False: the gathered comparison establishes the
    # replicated output itself — same rationale as zero_sgd_update
    sm = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                   check_rep=False)
    return jax.jit(lambda arrays: sm(arrays))


# -- accounting --------------------------------------------------------------

def _per_device_bytes(jx):
    """Max bytes any single device holds of ``jx`` (a jax Array):
    ``nbytes/world`` for a row-sharded state, ``nbytes`` for a
    replicated one — the quantity the ZeRO HBM claim is about."""
    per_dev = {}
    try:
        shards = jx.addressable_shards
    except AttributeError:
        return int(jx.nbytes)
    for s in shards:
        per_dev[s.device] = per_dev.get(s.device, 0) + int(s.data.nbytes)
    return max(per_dev.values()) if per_dev else int(jx.nbytes)


def optimizer_state_hbm(module):
    """``(per_device_bytes, total_logical_bytes)`` of the module's local
    updater states — the attribution the ZeRO acceptance pins (per-device
    optimizer-state HBM drops ~world-size vs the replicated baseline,
    where the two numbers are equal).  Complements the compiled-program
    view: with ``MXNET_PERF_ATTRIB=1`` the fused mesh step's
    per-partition ``argument_bytes`` in the :mod:`~mxnet_tpu.perfdebug`
    attribution tables shrinks by the same factor."""
    updater = getattr(module, "_updater", None)
    if updater is None:
        return (0, 0)
    per_dev = 0
    total = 0

    def walk(state):
        nonlocal per_dev, total
        if state is None:
            return
        if isinstance(state, (tuple, list)):
            for s in state:
                walk(s)
            return
        jx = getattr(state, "_jx", None)
        if jx is None:
            return
        per_dev += _per_device_bytes(jx)
        total += int(jx.nbytes)

    for state in updater.states.values():
        walk(state)
    return (per_dev, total)
