"""Bounded retry with exponential backoff — the shared transport policy.

Extracted from the ad-hoc sleep/retry loops in ``kvstore.py`` so every
reconnect path (worker connect, register, explicit ``reconnect()``) shares
one tested policy: exponential backoff with deterministic-free jitter,
capped per-attempt delay, and a hard wall-clock deadline after which the
last error propagates unchanged.
"""

from __future__ import annotations

import os
import random
import time

from . import telemetry as _telemetry

__all__ = ["RetryPolicy", "retry_call", "total_deadline_cap"]


def _jitter_rng():
    """The jitter source: the global ``random`` module normally (herd
    de-sync wants genuine process entropy), but a PRIVATE ``Random``
    seeded from ``MXNET_CHAOS_SEED`` when the chaos harness sets it —
    chaos replays of reconnect/rejoin storms must draw identical backoff
    schedules, and seeding the global module would perturb every other
    consumer of ``random`` in the process."""
    seed = os.environ.get("MXNET_CHAOS_SEED")
    if not seed:
        return random
    try:
        return random.Random(int(seed))
    except ValueError:
        return random.Random(seed)


def total_deadline_cap():
    """The process-wide cumulative retry ceiling
    (``MXNET_RETRY_TOTAL_DEADLINE``, seconds; None when unset/invalid).
    A fleet-wide guardrail: whatever per-site deadline a retry loop
    picked, the CUMULATIVE wall clock across its attempts can never
    exceed this — repeated transient failures (a flapping server that
    accepts then drops every connect) otherwise compound per-attempt
    backoff into an effectively unbounded stall that only the hang
    watchdog would ever surface."""
    raw = os.environ.get("MXNET_RETRY_TOTAL_DEADLINE")
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


class RetryPolicy:
    """Backoff schedule + deadline.

    Parameters
    ----------
    deadline : float or None
        Wall-clock budget in seconds from the first attempt.  When the
        budget is exhausted the last exception propagates.  None retries
        forever (callers should almost always set one).  ``deadline_s``
        is an accepted alias.  Either way the EFFECTIVE deadline is
        capped by ``MXNET_RETRY_TOTAL_DEADLINE`` when that is set — the
        cumulative cross-attempt ceiling no call site can opt out of.
    base_delay / max_delay : float
        First sleep and per-sleep cap (seconds); delays double each retry.
    jitter : float
        Fraction of the delay randomized away (0..1): a delay ``d`` sleeps
        ``d * (1 - jitter * random())``, de-synchronizing worker herds that
        all lost the same server.
    max_attempts : int or None
        Optional attempt cap on top of the deadline.
    """

    def __init__(self, deadline=None, base_delay=0.1, max_delay=2.0,
                 jitter=0.5, max_attempts=None, deadline_s=None):
        if deadline is None:
            deadline = deadline_s
        cap = total_deadline_cap()
        if cap is not None:
            deadline = cap if deadline is None else min(deadline, cap)
        self.deadline = deadline
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self.max_attempts = max_attempts

    def delays(self):
        """Yield sleep durations; the *caller* enforces the deadline (it
        knows when the first attempt started).  Each generator draws its
        jitter from :func:`_jitter_rng` — the global ``random`` module
        (herd de-sync) unless ``MXNET_CHAOS_SEED`` pins a private,
        replayable stream."""
        rng = _jitter_rng()
        d = self.base_delay
        while True:
            yield d * (1.0 - self.jitter * rng.random())
            d = min(d * 2.0, self.max_delay)


def retry_call(fn, retry_on=(OSError,), policy=None, retry_if=None,
               on_retry=None, start=None, metric=None, **policy_kwargs):
    """Call ``fn()`` until it returns, retrying listed exceptions.

    Parameters
    ----------
    fn : callable
        Zero-argument callable to attempt.
    retry_on : tuple of exception types
        Exceptions that trigger a retry; anything else propagates at once.
    policy : RetryPolicy, optional
        Schedule + deadline.  ``policy_kwargs`` (``deadline=...`` etc.)
        construct one when not given.
    retry_if : callable(exc) -> bool, optional
        Extra predicate — a matching exception type is only retried when
        this also returns True (e.g. "only idempotent registrations").
    on_retry : callable(exc, attempt), optional
        Observer invoked before each sleep (cleanup/logging hook).
    start : float (time.monotonic()), optional
        Deadline anchor.  Several ``retry_call``s sharing one ``start``
        share one absolute deadline (e.g. connect-to-N-servers then
        register, all within a single budget).
    metric : str, optional
        Telemetry site label: each retry bumps the ``retry.count`` and
        ``retry.backoff_seconds`` counters labeled ``site=<metric>``
        (no-op while telemetry is disabled).

    The deadline is measured from ``start`` (default: the first attempt);
    when it expires, the exception that caused the final retry propagates
    unchanged.
    """
    if policy is None:
        policy = RetryPolicy(**policy_kwargs)
    if start is None:
        start = time.monotonic()
    attempt = 0
    for delay in policy.delays():
        attempt += 1
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            if retry_if is not None and not retry_if(e):
                raise
            if policy.max_attempts is not None \
                    and attempt >= policy.max_attempts:
                raise
            now = time.monotonic()
            if policy.deadline is not None \
                    and now + delay > start + policy.deadline:
                raise
            if metric is not None and _telemetry.enabled():
                _telemetry.inc("retry.count", site=metric)
                _telemetry.inc("retry.backoff_seconds", delay, site=metric)
            if on_retry is not None:
                on_retry(e, attempt)
            time.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
