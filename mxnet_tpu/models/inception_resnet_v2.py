"""Inception-ResNet-v2 (reference ``example/image-classification/symbols/
inception-resnet-v2.py`` — Szegedy et al., "Inception-v4, Inception-ResNet
and the Impact of Residual Connections on Learning")."""

from .. import symbol as sym


def ConvFactory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                act_type="relu", name=None):
    conv = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, name="conv_%s" % name)
    bn = sym.BatchNorm(conv, fix_gamma=False, name="bn_%s" % name)
    if act_type is None:
        return bn
    return sym.Activation(bn, act_type=act_type, name="relu_%s" % name)


def block35(net, scale=1.0, name=None):
    """Inception-ResNet-A (35x35 grid)."""
    tower_conv = ConvFactory(net, 32, (1, 1), name="%s_b0_1x1" % name)
    t1 = ConvFactory(net, 32, (1, 1), name="%s_b1_1x1" % name)
    t1 = ConvFactory(t1, 32, (3, 3), pad=(1, 1), name="%s_b1_3x3" % name)
    t2 = ConvFactory(net, 32, (1, 1), name="%s_b2_1x1" % name)
    t2 = ConvFactory(t2, 48, (3, 3), pad=(1, 1), name="%s_b2_3x3a" % name)
    t2 = ConvFactory(t2, 64, (3, 3), pad=(1, 1), name="%s_b2_3x3b" % name)
    mixed = sym.Concat(tower_conv, t1, t2, name="%s_concat" % name)
    up = ConvFactory(mixed, 320, (1, 1), act_type=None,
                     name="%s_up" % name)
    net = net + up * scale
    return sym.Activation(net, act_type="relu", name="%s_relu" % name)


def block17(net, scale=1.0, name=None):
    """Inception-ResNet-B (17x17 grid)."""
    t0 = ConvFactory(net, 192, (1, 1), name="%s_b0_1x1" % name)
    t1 = ConvFactory(net, 128, (1, 1), name="%s_b1_1x1" % name)
    t1 = ConvFactory(t1, 160, (1, 7), pad=(0, 3), name="%s_b1_1x7" % name)
    t1 = ConvFactory(t1, 192, (7, 1), pad=(3, 0), name="%s_b1_7x1" % name)
    mixed = sym.Concat(t0, t1, name="%s_concat" % name)
    up = ConvFactory(mixed, 1088, (1, 1), act_type=None, name="%s_up" % name)
    net = net + up * scale
    return sym.Activation(net, act_type="relu", name="%s_relu" % name)


def block8(net, scale=1.0, with_act=True, name=None):
    """Inception-ResNet-C (8x8 grid)."""
    t0 = ConvFactory(net, 192, (1, 1), name="%s_b0_1x1" % name)
    t1 = ConvFactory(net, 192, (1, 1), name="%s_b1_1x1" % name)
    t1 = ConvFactory(t1, 224, (1, 3), pad=(0, 1), name="%s_b1_1x3" % name)
    t1 = ConvFactory(t1, 256, (3, 1), pad=(1, 0), name="%s_b1_3x1" % name)
    mixed = sym.Concat(t0, t1, name="%s_concat" % name)
    up = ConvFactory(mixed, 2080, (1, 1), act_type=None, name="%s_up" % name)
    net = net + up * scale
    if with_act:
        net = sym.Activation(net, act_type="relu", name="%s_relu" % name)
    return net


def get_symbol(num_classes=1000, num_35=10, num_17=20, num_8=9, **kwargs):
    """Full net; ``num_35/17/20/8`` repeat counts default to the paper's
    10/20/9 (trim for quick tests)."""
    data = sym.Variable("data")
    # stem
    net = ConvFactory(data, 32, (3, 3), stride=(2, 2), name="stem_1a")
    net = ConvFactory(net, 32, (3, 3), name="stem_2a")
    net = ConvFactory(net, 64, (3, 3), pad=(1, 1), name="stem_2b")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="stem_pool1")
    net = ConvFactory(net, 80, (1, 1), name="stem_3b")
    net = ConvFactory(net, 192, (3, 3), name="stem_4a")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="stem_pool2")
    # mixed 5b
    t0 = ConvFactory(net, 96, (1, 1), name="m5b_b0")
    t1 = ConvFactory(net, 48, (1, 1), name="m5b_b1a")
    t1 = ConvFactory(t1, 64, (5, 5), pad=(2, 2), name="m5b_b1b")
    t2 = ConvFactory(net, 64, (1, 1), name="m5b_b2a")
    t2 = ConvFactory(t2, 96, (3, 3), pad=(1, 1), name="m5b_b2b")
    t2 = ConvFactory(t2, 96, (3, 3), pad=(1, 1), name="m5b_b2c")
    t3 = sym.Pooling(net, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg", name="m5b_pool")
    t3 = ConvFactory(t3, 64, (1, 1), name="m5b_b3")
    net = sym.Concat(t0, t1, t2, t3, name="mixed_5b")
    for i in range(num_35):
        net = block35(net, scale=0.17, name="irA_%d" % i)
    # reduction A
    t0 = ConvFactory(net, 384, (3, 3), stride=(2, 2), name="redA_b0")
    t1 = ConvFactory(net, 256, (1, 1), name="redA_b1a")
    t1 = ConvFactory(t1, 256, (3, 3), pad=(1, 1), name="redA_b1b")
    t1 = ConvFactory(t1, 384, (3, 3), stride=(2, 2), name="redA_b1c")
    t2 = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     name="redA_pool")
    net = sym.Concat(t0, t1, t2, name="reduction_a")
    for i in range(num_17):
        net = block17(net, scale=0.10, name="irB_%d" % i)
    # reduction B
    t0 = ConvFactory(net, 256, (1, 1), name="redB_b0a")
    t0 = ConvFactory(t0, 384, (3, 3), stride=(2, 2), name="redB_b0b")
    t1 = ConvFactory(net, 256, (1, 1), name="redB_b1a")
    t1 = ConvFactory(t1, 288, (3, 3), stride=(2, 2), name="redB_b1b")
    t2 = ConvFactory(net, 256, (1, 1), name="redB_b2a")
    t2 = ConvFactory(t2, 288, (3, 3), pad=(1, 1), name="redB_b2b")
    t2 = ConvFactory(t2, 320, (3, 3), stride=(2, 2), name="redB_b2c")
    t3 = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     name="redB_pool")
    net = sym.Concat(t0, t1, t2, t3, name="reduction_b")
    for i in range(num_8):
        net = block8(net, scale=0.20, name="irC_%d" % i)
    net = block8(net, with_act=False, name="irC_final")
    net = ConvFactory(net, 1536, (1, 1), name="final_conv")
    net = sym.Pooling(net, kernel=(8, 8), global_pool=True, pool_type="avg",
                      name="global_pool")
    net = sym.Flatten(net, name="flatten")
    net = sym.Dropout(net, p=0.2, name="dropout")
    fc = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
