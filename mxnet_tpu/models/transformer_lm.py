"""Decode-capable transformer LM — the served autoregressive workload.

``parallel/lm.py`` is the *training* flagship (dp x tp x pp x sp x ep in
one SPMD step); this module is its serving-side counterpart: a compact
decoder-only transformer whose forward math is split exactly along the
line a continuous-batching server needs (docs/serving.md "Continuous
batching & replica pool"):

* :func:`prefill_kv` — run the full prompt once, return the last-token
  logits plus the per-layer K/V rows to seed a slot of the engine's
  device-resident cache;
* :func:`decode_step_math` — ONE token for ALL ``S`` cache slots at
  once: scatter the incoming token's K/V into each slot's cache row,
  attend over ``positions <= length`` and produce ``(S, vocab)``
  logits.  Fixed shapes in, fixed shapes out — the function compiles
  once per ``(S, max_len)`` and never again
  (:mod:`mxnet_tpu.serving.decode` wraps it with sampling and slot
  state into the single jitted step);
* :func:`forward_logits` — plain batched teacher-forcing forward, the
  ground truth the decode path is pinned bit-compatible against
  (``tests/test_decode.py``: greedy decode == argmax of the full
  forward).

The math is deliberately single-device per replica — multi-replica
throughput comes from :class:`~mxnet_tpu.serving.pool.ReplicaPool`
spreading engines over ``jax.devices()``, not from sharding one model.
"""

from __future__ import annotations

import io
import json
from collections import namedtuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LMConfig", "init_params", "forward_logits", "prefill_kv",
           "decode_step_math", "prefill_kv_paged", "decode_step_paged",
           "params_to_blob", "params_from_blob"]

#: model hyperparameters; ``max_len`` bounds the KV cache (and therefore
#: prompt + generated length), ``eos_id`` is the token that retires a
#: sequence early
LMConfig = namedtuple("LMConfig", ["vocab", "embed", "heads", "layers",
                                   "ffn", "max_len", "eos_id"])


def init_params(cfg, seed=0, dtype=jnp.float32):
    """Parameter pytree (host -> the caller ``device_put``s it where the
    replica lives).  Per-layer weights are stacked on axis 0 so the
    pytree stays flat and a layer loop indexes rows."""
    if cfg.embed % cfg.heads:
        raise ValueError("embed=%d not divisible by heads=%d"
                         % (cfg.embed, cfg.heads))
    rs = np.random.RandomState(seed)

    def nrm(*shape, s=0.05):
        return jnp.asarray(rs.normal(0, s, shape).astype(np.float32),
                           dtype=dtype)

    L, E, F = cfg.layers, cfg.embed, cfg.ffn
    return {
        "embed": nrm(cfg.vocab, E),
        "pos": nrm(cfg.max_len, E),
        "head": nrm(E, cfg.vocab),
        "ln_f": jnp.ones((E,), dtype),
        "blocks": {
            "ln1": jnp.ones((L, E), dtype),
            "qkv_w": nrm(L, E, 3 * E),
            "out_w": nrm(L, E, E),
            "ln2": jnp.ones((L, E), dtype),
            "up_w": nrm(L, E, F),
            "down_w": nrm(L, F, E),
        },
    }


def _rmsnorm(x, g):
    return x * g * jax.lax.rsqrt(
        (x.astype(jnp.float32) ** 2).mean(-1, keepdims=True)
        + 1e-6).astype(x.dtype)


def _layer(blocks, l):
    return {k: v[l] for k, v in blocks.items()}


def forward_logits(cfg, params, tokens):
    """Teacher-forcing forward: ``tokens (B, T) int32 -> (B, T, vocab)``
    float32 logits — training/eval and the decode-parity ground truth."""
    b, t = tokens.shape
    pos = jnp.arange(t)
    x = params["embed"][tokens] + params["pos"][pos][None]
    causal = pos[None, :] <= pos[:, None]            # (q, k)
    hd = cfg.embed // cfg.heads
    scale = 1.0 / np.sqrt(hd)
    for l in range(cfg.layers):
        p = _layer(params["blocks"], l)
        h = _rmsnorm(x, p["ln1"])
        qkv = jnp.einsum("bte,ef->btf", h, p["qkv_w"])
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(a):
            return a.reshape(b, t, cfg.heads, hd)

        scores = jnp.einsum("bqhd,bkhd->bhqk", heads(q), heads(k)) * scale
        att = jax.nn.softmax(
            jnp.where(causal[None, None], scores, jnp.float32(-1e30)),
            axis=-1)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", att, heads(v))
        x = x + jnp.einsum("bte,ef->btf",
                           ctx.reshape(b, t, cfg.embed), p["out_w"])
        h = _rmsnorm(x, p["ln2"])
        x = x + jnp.einsum("btf,fe->bte",
                           jax.nn.gelu(jnp.einsum("bte,ef->btf", h,
                                                  p["up_w"])), p["down_w"])
    x = _rmsnorm(x, params["ln_f"])
    return jnp.einsum("bte,ev->btv", x, params["head"]).astype(jnp.float32)


def prefill_kv(cfg, params, tokens, length):
    """One prompt through the model: ``tokens (P,) int32`` (bucket-padded,
    ``length`` real tokens) -> ``(last_logits (vocab,), ks, vs)`` where
    ``ks``/``vs`` are per-layer tuples of ``(P, heads, head_dim)`` cache
    rows for positions ``0..P-1``.  Rows past ``length`` hold pad-token
    K/V — the decode attention mask (``position <= slot length``) never
    reads them before the decode step itself overwrites them in place.
    """
    (p,) = tokens.shape
    pos = jnp.arange(p)
    x = params["embed"][tokens] + params["pos"][pos]
    causal = pos[None, :] <= pos[:, None]
    hd = cfg.embed // cfg.heads
    scale = 1.0 / np.sqrt(hd)
    ks, vs = [], []
    for l in range(cfg.layers):
        pl = _layer(params["blocks"], l)
        h = _rmsnorm(x, pl["ln1"])
        qkv = jnp.einsum("te,ef->tf", h, pl["qkv_w"])
        q, k, v = (a.reshape(p, cfg.heads, hd)
                   for a in jnp.split(qkv, 3, axis=-1))
        scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
        att = jax.nn.softmax(
            jnp.where(causal[None], scores, jnp.float32(-1e30)), axis=-1)
        ctx = jnp.einsum("hqk,khd->qhd", att, v)
        x = x + jnp.einsum("te,ef->tf",
                           ctx.reshape(p, cfg.embed), pl["out_w"])
        h = _rmsnorm(x, pl["ln2"])
        x = x + jnp.einsum("tf,fe->te",
                           jax.nn.gelu(jnp.einsum("te,ef->tf", h,
                                                  pl["up_w"])),
                           pl["down_w"])
        ks.append(k)
        vs.append(v)
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("te,ev->tv", x, params["head"]).astype(jnp.float32)
    last = jnp.take(logits, jnp.clip(length - 1, 0, p - 1), axis=0)
    return last, tuple(ks), tuple(vs)


def decode_step_math(cfg, params, cache_k, cache_v, last_tok, lengths):
    """One decode token for all ``S`` slots.

    ``cache_k``/``cache_v``: per-layer tuples of ``(S, max_len, heads,
    head_dim)``; ``last_tok (S,) int32`` is each slot's most recent
    token (prompt tail after prefill, previous sample afterwards);
    ``lengths (S,) int32`` is each slot's cache fill — the position the
    incoming token's K/V is scattered to, and the inclusive attention
    horizon.  Returns ``(logits (S, vocab), new_cache_k, new_cache_v)``.

    Inactive slots ride along (fixed shape => no recompile): their
    scatter lands on a row the mask makes unreachable until a real
    write replaces it, and their logits are discarded host-side.
    """
    (s, m) = cache_k[0].shape[:2]
    hd = cfg.embed // cfg.heads
    scale = 1.0 / np.sqrt(hd)
    rows = jnp.arange(s)
    kpos = jnp.arange(m)
    pos = jnp.clip(lengths, 0, cfg.max_len - 1)
    x = params["embed"][last_tok] + params["pos"][pos]
    new_k, new_v = [], []
    for l in range(cfg.layers):
        pl = _layer(params["blocks"], l)
        h = _rmsnorm(x, pl["ln1"])
        qkv = jnp.einsum("se,ef->sf", h, pl["qkv_w"])
        q, k, v = (a.reshape(s, cfg.heads, hd)
                   for a in jnp.split(qkv, 3, axis=-1))
        ck = cache_k[l].at[rows, pos].set(k)
        cv = cache_v[l].at[rows, pos].set(v)
        scores = jnp.einsum("shd,smhd->shm", q, ck) * scale
        mask = kpos[None, None, :] <= pos[:, None, None]
        att = jax.nn.softmax(
            jnp.where(mask, scores, jnp.float32(-1e30)), axis=-1)
        ctx = jnp.einsum("shm,smhd->shd", att, cv)
        x = x + jnp.einsum("se,ef->sf",
                           ctx.reshape(s, cfg.embed), pl["out_w"])
        h = _rmsnorm(x, pl["ln2"])
        x = x + jnp.einsum("sf,fe->se",
                           jax.nn.gelu(jnp.einsum("se,ef->sf", h,
                                                  pl["up_w"])),
                           pl["down_w"])
        new_k.append(ck)
        new_v.append(cv)
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("se,ev->sv", x, params["head"]).astype(jnp.float32)
    return logits, tuple(new_k), tuple(new_v)


def prefill_kv_paged(cfg, params, pool_k, pool_v, table, tokens, start,
                     length):
    """Suffix prefill through a block table — the paged twin of
    :func:`prefill_kv` (``mxnet_tpu.serving.kvblocks`` owns the block
    bookkeeping; this is pure math).

    ``pool_k``/``pool_v``: per-layer tuples of ``(num_blocks,
    block_size, heads, head_dim)`` pool rows; ``table (max_blocks,)
    int32`` maps the slot's logical block index to a pool row (0 = the
    reserved scratch block, where unallocated entries point).
    ``tokens (P,) int32`` is the bucket-padded transcript SUFFIX
    occupying absolute positions ``start .. start+P-1``: ``start = 0``
    is a cold prefill, ``start > 0`` is a prefix-cache hit that runs
    ZERO compute for the shared positions — their K/V is already
    resident in the table's blocks and is only gathered for attention.
    ``length`` is the absolute transcript length.  Returns
    ``(last_logits (vocab,), new_pool_k, new_pool_v)``.

    Bit-identity with the dense path is by construction: K/V rows are
    scattered into the pool, gathered back through the table and
    statically sliced to ``max_len``, so scores, mask and softmax see
    EXACTLY the shapes :func:`decode_step_math`'s attention sees; lanes
    past a row's horizon are exact zeros under the ``-1e30`` mask, and
    unallocated lanes read scratch garbage that the mask also zeroes.
    Bucket-pad rows scatter to the scratch block or to not-yet-read
    rows past ``length`` — the same never-read discipline as the dense
    prefill's pad rows.
    """
    (p,) = tokens.shape
    (mb,) = table.shape
    bs = pool_k[0].shape[1]
    m = cfg.max_len
    hd = cfg.embed // cfg.heads
    scale = 1.0 / np.sqrt(hd)
    pos = start + jnp.arange(p)            # absolute positions
    posc = jnp.clip(pos, 0, m - 1)         # only pad rows ever clamp
    blk = table[posc // bs]
    off = posc % bs
    x = params["embed"][tokens] + params["pos"][posc]
    kpos = jnp.arange(m)
    mask = kpos[None, :] <= pos[:, None]
    new_k, new_v = [], []
    for l in range(cfg.layers):
        pl = _layer(params["blocks"], l)
        h = _rmsnorm(x, pl["ln1"])
        qkv = jnp.einsum("te,ef->tf", h, pl["qkv_w"])
        q, k, v = (a.reshape(p, cfg.heads, hd)
                   for a in jnp.split(qkv, 3, axis=-1))
        pk = pool_k[l].at[blk, off].set(k)
        pv = pool_v[l].at[blk, off].set(v)
        ck = pk[table].reshape(mb * bs, cfg.heads, hd)[:m]
        cv = pv[table].reshape(mb * bs, cfg.heads, hd)[:m]
        scores = jnp.einsum("qhd,khd->hqk", q, ck) * scale
        att = jax.nn.softmax(
            jnp.where(mask[None], scores, jnp.float32(-1e30)), axis=-1)
        ctx = jnp.einsum("hqk,khd->qhd", att, cv)
        x = x + jnp.einsum("te,ef->tf",
                           ctx.reshape(p, cfg.embed), pl["out_w"])
        h = _rmsnorm(x, pl["ln2"])
        x = x + jnp.einsum("tf,fe->te",
                           jax.nn.gelu(jnp.einsum("te,ef->tf", h,
                                                  pl["up_w"])),
                           pl["down_w"])
        new_k.append(pk)
        new_v.append(pv)
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("te,ev->tv", x, params["head"]).astype(jnp.float32)
    last = jnp.take(logits, jnp.clip(length - 1 - start, 0, p - 1),
                    axis=0)
    return last, tuple(new_k), tuple(new_v)


def decode_step_paged(cfg, params, pool_k, pool_v, tables, last_tok,
                      lengths):
    """One decode token for all ``S`` slots through per-slot block
    tables — the paged twin of :func:`decode_step_math`.

    ``tables (S, max_blocks) int32`` names each slot's pool rows; the
    incoming token's K/V scatters into the block covering position
    ``lengths`` (the engine allocates that block before dispatch), the
    slot's whole table is gathered and statically sliced to
    ``(S, max_len)``, and attention proceeds exactly as the dense
    step's — same shapes, same mask, same floats.  Inactive slots hold
    all-zero tables: their scatter lands in the scratch block and their
    gathered lanes are mask-dead, the paged rendition of the dense
    step's unreachable-row idiom.  Fixed shapes throughout — ONE
    compile per ``(S, max_len, num_blocks, block_size)``, ever.
    """
    s, mb = tables.shape
    bs = pool_k[0].shape[1]
    m = cfg.max_len
    hd = cfg.embed // cfg.heads
    scale = 1.0 / np.sqrt(hd)
    rows = jnp.arange(s)
    kpos = jnp.arange(m)
    pos = jnp.clip(lengths, 0, m - 1)
    wblk = tables[rows, pos // bs]
    woff = pos % bs
    x = params["embed"][last_tok] + params["pos"][pos]
    new_k, new_v = [], []
    for l in range(cfg.layers):
        pl = _layer(params["blocks"], l)
        h = _rmsnorm(x, pl["ln1"])
        qkv = jnp.einsum("se,ef->sf", h, pl["qkv_w"])
        q, k, v = (a.reshape(s, cfg.heads, hd)
                   for a in jnp.split(qkv, 3, axis=-1))
        pk = pool_k[l].at[wblk, woff].set(k)
        pv = pool_v[l].at[wblk, woff].set(v)
        ck = pk[tables].reshape(s, mb * bs, cfg.heads, hd)[:, :m]
        cv = pv[tables].reshape(s, mb * bs, cfg.heads, hd)[:, :m]
        scores = jnp.einsum("shd,smhd->shm", q, ck) * scale
        mask = kpos[None, None, :] <= pos[:, None, None]
        att = jax.nn.softmax(
            jnp.where(mask, scores, jnp.float32(-1e30)), axis=-1)
        ctx = jnp.einsum("shm,smhd->shd", att, cv)
        x = x + jnp.einsum("se,ef->sf",
                           ctx.reshape(s, cfg.embed), pl["out_w"])
        h = _rmsnorm(x, pl["ln2"])
        x = x + jnp.einsum("sf,fe->se",
                           jax.nn.gelu(jnp.einsum("se,ef->sf", h,
                                                  pl["up_w"])),
                           pl["down_w"])
        new_k.append(pk)
        new_v.append(pv)
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("se,ev->sv", x, params["head"]).astype(jnp.float32)
    return logits, tuple(new_k), tuple(new_v)


def params_to_blob(cfg, params):
    """Serialize ``(cfg, params)`` to one npz blob (the serving publish
    payload format, :func:`mxnet_tpu.serving.save_model` convention)."""
    flat = {"__config__": np.frombuffer(
        json.dumps(cfg._asdict()).encode(), np.uint8)}
    for k, v in params.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat["%s.%s" % (k, k2)] = np.asarray(v2)
        else:
            flat[k] = np.asarray(v)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    return buf.getvalue()


def params_from_blob(blob):
    """Inverse of :func:`params_to_blob`: ``(cfg, params)``."""
    with np.load(io.BytesIO(blob)) as z:
        cfg = LMConfig(**json.loads(bytes(z["__config__"]).decode()))
        params = {"blocks": {}}
        for k in z.files:
            if k == "__config__":
                continue
            if k.startswith("blocks."):
                params["blocks"][k.split(".", 1)[1]] = jnp.asarray(z[k])
            else:
                params[k] = jnp.asarray(z[k])
    return cfg, params
