"""SSD-VGG16-reduced detector (BASELINE config 4).

Reference: ``example/ssd/symbol/symbol_vgg16_reduced.py`` (loss graph
:121-139, deploy :173) and ``example/ssd/symbol/common.py:164``
(``multibox_layer``), ``example/ssd/train/metric.py:5`` (MultiBoxMetric).

Topology: VGG16 with fc6/fc7 as convs (fc6 dilated 6), extra feature
pyramid conv8-conv10 + global pool; 6 prediction scales with per-scale
anchor sizes/ratios; training graph = MultiBoxTarget →
SoftmaxOutput(cls, valid-normalized, hard-negative-ignored) +
smooth_l1/MakeLoss(loc) + zero-grad MakeLoss(cls_target) for metric
plumbing.  On TPU the entire multi-loss graph (priors, matching, NMS-free
training path) stays inside one XLA computation.
"""

from __future__ import annotations

import numpy as np

from .. import symbol as sym
from ..metric import EvalMetric as _EvalMetric

__all__ = ["get_symbol_train", "get_symbol", "multibox_layer",
           "MultiBoxMetric"]


def _conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1),
              stride=(1, 1), act_type="relu"):
    conv = sym.Convolution(data=data, kernel=kernel, pad=pad, stride=stride,
                           num_filter=num_filter, name="conv%s" % name)
    return sym.Activation(data=conv, act_type=act_type, name="relu%s" % name)


def _vgg_block(data, idx, n_convs, num_filter, pool_stride=(2, 2),
               pooling_convention="valid"):
    out = data
    for i in range(1, n_convs + 1):
        out = _conv_act(out, "%d_%d" % (idx, i), num_filter)
    pool = sym.Pooling(data=out, pool_type="max", kernel=(2, 2),
                       stride=pool_stride,
                       pooling_convention=pooling_convention,
                       name="pool%d" % idx)
    return out, pool


def multibox_layer(from_layers, num_classes, sizes, ratios, normalization,
                   num_channels, clip=True):
    """Per-scale cls/loc conv heads + anchors (reference
    ``example/ssd/symbol/common.py:164``)."""
    assert num_classes > 0
    num_channels = list(num_channels)
    loc_layers, cls_layers, anchor_layers = [], [], []
    num_label_classes = num_classes + 1  # background = 0
    for k, from_layer in enumerate(from_layers):
        name = "multibox%d" % k
        if normalization[k] > 0:
            from_layer = sym.L2Normalization(data=from_layer,
                                             mode="channel",
                                             name="%s_norm" % name)
            from ..initializer import Constant

            scale = sym.Variable(
                "%s_scale" % name, shape=(1, num_channels.pop(0), 1, 1),
                init=Constant(value=float(normalization[k])), wd_mult=0.1)
            from_layer = sym.broadcast_mul(scale, from_layer)
        num_anchors = len(sizes[k]) + len(ratios[k]) - 1

        loc_pred = sym.Convolution(data=from_layer, kernel=(3, 3),
                                   pad=(1, 1), num_filter=num_anchors * 4,
                                   name="%s_loc_pred_conv" % name)
        loc_pred = sym.transpose(loc_pred, axes=(0, 2, 3, 1))
        loc_layers.append(sym.Flatten(data=loc_pred))

        cls_pred = sym.Convolution(
            data=from_layer, kernel=(3, 3), pad=(1, 1),
            num_filter=num_anchors * num_label_classes,
            name="%s_cls_pred_conv" % name)
        cls_pred = sym.transpose(cls_pred, axes=(0, 2, 3, 1))
        cls_layers.append(sym.Flatten(data=cls_pred))

        anchors = getattr(sym, "_contrib_MultiBoxPrior")(
            from_layer, sizes=tuple(sizes[k]), ratios=tuple(ratios[k]),
            clip=clip, name="%s_anchors" % name)
        anchor_layers.append(sym.Reshape(data=anchors, shape=(0, -1, 4)))

    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_layers, dim=1)
    cls_preds = sym.Reshape(data=cls_preds, shape=(0, -1, num_label_classes))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1),
                              name="multibox_cls_pred")
    anchor_boxes = sym.Concat(*anchor_layers, dim=1,
                              name="multibox_anchors")
    return [loc_preds, cls_preds, anchor_boxes]


def _vgg16_reduced_features(data):
    """VGG16-reduced backbone; returns (relu4_3, relu7, feature pyramid)."""
    _, pool1 = _vgg_block(data, 1, 2, 64)
    _, pool2 = _vgg_block(pool1, 2, 2, 128)
    _, pool3 = _vgg_block(pool2, 3, 3, 256, pooling_convention="full")
    relu4_3, pool4 = _vgg_block(pool3, 4, 3, 512)
    relu5_3, _ = _vgg_block(pool4, 5, 3, 512)
    pool5 = sym.Pooling(data=relu5_3, pool_type="max", kernel=(3, 3),
                        stride=(1, 1), pad=(1, 1), name="pool5")
    # fc6/fc7 as convolutions (fc6 dilated 6 — the "reduced" trick)
    conv6 = sym.Convolution(data=pool5, kernel=(3, 3), pad=(6, 6),
                            dilate=(6, 6), num_filter=1024, name="fc6")
    relu6 = sym.Activation(data=conv6, act_type="relu", name="relu6")
    conv7 = sym.Convolution(data=relu6, kernel=(1, 1), num_filter=1024,
                            name="fc7")
    relu7 = sym.Activation(data=conv7, act_type="relu", name="relu7")

    relu8_1 = _conv_act(relu7, "8_1", 256, kernel=(1, 1), pad=(0, 0))
    relu8_2 = _conv_act(relu8_1, "8_2", 512, stride=(2, 2))
    relu9_1 = _conv_act(relu8_2, "9_1", 128, kernel=(1, 1), pad=(0, 0))
    relu9_2 = _conv_act(relu9_1, "9_2", 256, stride=(2, 2))
    relu10_1 = _conv_act(relu9_2, "10_1", 128, kernel=(1, 1), pad=(0, 0))
    relu10_2 = _conv_act(relu10_1, "10_2", 256, stride=(2, 2))
    pool10 = sym.Pooling(data=relu10_2, pool_type="avg", global_pool=True,
                         kernel=(1, 1), name="pool10")
    return [relu4_3, relu7, relu8_2, relu9_2, relu10_2, pool10]


# per-scale anchor config (reference symbol_vgg16_reduced.py:111-114)
_SIZES = [[.1], [.2, .276], [.38, .461], [.56, .644], [.74, .825],
          [.92, 1.01]]
_RATIOS = [[1, 2, .5]] + [[1, 2, .5, 3, 1. / 3]] * 5
_NORMALIZATIONS = [20, -1, -1, -1, -1, -1]
_NUM_CHANNELS = [512]


def get_symbol_train(num_classes=20, **kwargs):
    """Training graph (reference ``symbol_vgg16_reduced.py:13-144``)."""
    data = sym.Variable("data")
    label = sym.Variable("label")
    from_layers = _vgg16_reduced_features(data)
    loc_preds, cls_preds, anchor_boxes = multibox_layer(
        from_layers, num_classes, _SIZES, _RATIOS, _NORMALIZATIONS,
        _NUM_CHANNELS, clip=True)

    tmp = getattr(sym, "_contrib_MultiBoxTarget")(
        anchor_boxes, label, cls_preds, overlap_threshold=.5,
        ignore_label=-1, negative_mining_ratio=3,
        minimum_negative_samples=0, negative_mining_thresh=.5,
        variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")
    loc_target, loc_target_mask, cls_target = tmp[0], tmp[1], tmp[2]

    cls_prob = sym.SoftmaxOutput(data=cls_preds, label=cls_target,
                                 ignore_label=-1, use_ignore=True,
                                 grad_scale=3., multi_output=True,
                                 normalization="valid", name="cls_prob")
    loc_loss_ = sym.smooth_l1(data=loc_target_mask * (loc_preds - loc_target),
                              scalar=1.0, name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, grad_scale=1.,
                            normalization="valid", name="loc_loss")
    cls_label = sym.MakeLoss(data=cls_target, grad_scale=0,
                             name="cls_label")
    return sym.Group([cls_prob, loc_loss, cls_label])


def get_symbol(num_classes=20, nms_thresh=0.5, force_suppress=True,
               nms_topk=400, **kwargs):
    """Deploy graph: shared features + MultiBoxDetection (reference
    ``symbol_vgg16_reduced.py:146-180``)."""
    net = get_symbol_train(num_classes)
    internals = net.get_internals()
    cls_preds = internals["multibox_cls_pred_output"]
    loc_preds = internals["multibox_loc_pred_output"]
    anchor_boxes = internals["multibox_anchors_output"]

    cls_prob = sym.SoftmaxActivation(data=cls_preds, mode="channel",
                                     name="cls_prob")
    return getattr(sym, "_contrib_MultiBoxDetection")(
        cls_prob, loc_preds, anchor_boxes, name="detection",
        nms_threshold=nms_thresh, force_suppress=force_suppress,
        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=nms_topk)


class MultiBoxMetric(_EvalMetric):
    """Cross-entropy + smooth-L1 training metric for the SSD loss group
    (reference ``example/ssd/train/metric.py:5`` — an ``EvalMetric``
    subclass so ``Module.fit(eval_metric=...)`` accepts it)."""

    def __init__(self, eps=1e-8):
        self.eps = eps
        self.name = ["CrossEntropy", "SmoothL1"]
        self.num = len(self.name)
        self.reset()

    def reset(self):
        self.num_inst = [0] * self.num
        self.sum_metric = [0.0] * self.num

    def update(self, labels, preds):
        cls_prob = preds[0].asnumpy()
        loc_loss = preds[1].asnumpy()
        cls_label = preds[2].asnumpy()
        valid_count = np.sum(cls_label >= 0)
        # overall accuracy & object accuracy
        label = cls_label.flatten().astype(np.int64)
        mask = np.where(label >= 0)[0]
        indices = np.int64(label[mask])
        prob = cls_prob.transpose((0, 2, 1)).reshape((-1, cls_prob.shape[1]))
        prob = prob[mask, indices]
        self.sum_metric[0] += (-np.log(prob + self.eps)).sum()
        self.num_inst[0] += valid_count
        # smoothl1loss
        self.sum_metric[1] += np.sum(loc_loss)
        self.num_inst[1] += valid_count

    def get(self):
        names = ["%s" % (self.name[i]) for i in range(self.num)]
        values = [s / max(1, n)
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return names, values
