"""Model zoo — symbol builders for the reference's example networks.

Reference: ``example/image-classification/symbols/`` (SURVEY §2.8): lenet,
mlp, alexnet, vgg, inception-bn, inception-v3, resnet, resnext; plus the rnn
and ssd model families in their own modules.

Each ``get_symbol(num_classes, **kwargs)`` returns a Symbol ending in
``SoftmaxOutput(name='softmax')`` exactly like the reference builders, so
``Module.fit`` training scripts port 1:1.
"""

from . import lenet, mlp, alexnet, vgg, resnet, inception_bn, inception_v3
from . import googlenet, inception_resnet_v2
from . import ssd_vgg16, rcnn
# decode-capable transformer LM (pure-jax functional, not a symbol
# builder): the serving decode tier's workload (docs/serving.md)
from . import transformer_lm  # noqa: F401

_BUILDERS = {
    "lenet": lenet.get_symbol,
    "mlp": mlp.get_symbol,
    "alexnet": alexnet.get_symbol,
    "vgg": vgg.get_symbol,
    "resnet": resnet.get_symbol,
    "inception-bn": inception_bn.get_symbol,
    "inception-v3": inception_v3.get_symbol,
    "resnext": resnet.get_symbol_resnext,
    "googlenet": googlenet.get_symbol,
    "inception-resnet-v2": inception_resnet_v2.get_symbol,
}


def get_symbol(network, num_classes=1000, **kwargs):
    """Dispatch like ``example/image-classification/train_*.py --network``."""
    if network not in _BUILDERS:
        raise ValueError("unknown network %r (have %s)"
                         % (network, sorted(_BUILDERS)))
    return _BUILDERS[network](num_classes=num_classes, **kwargs)
