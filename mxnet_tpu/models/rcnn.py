"""Faster R-CNN (VGG16 backbone) — reference ``example/rcnn/rcnn/symbol/
symbol_vgg.py`` (``get_vgg_rpn``/``get_vgg_train``/``get_vgg_test``).

The region pipeline uses the contrib ops: ``Proposal``
(``src/operator/contrib/proposal.cc``) to turn RPN scores + box deltas into
ROIs, then ``ROIPooling`` (``src/operator/roi_pooling.cc``) and the fc6/fc7
head.  Training uses the RPN losses (SoftmaxOutput on anchor labels +
smooth-L1 on box regression); the full end-to-end variant adds the per-ROI
cls/bbox losses on externally provided ROI targets, matching the reference's
alternate/approximate-joint training setup.
"""

from .. import symbol as sym


def _vgg_conv_body(data):
    """VGG16 conv1-conv5 (reference ``symbol_vgg.py:get_vgg_conv``)."""
    net = data
    for i, (blocks, filters) in enumerate(
            [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)], start=1):
        for j in range(blocks):
            net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                                  num_filter=filters,
                                  name="conv%d_%d" % (i, j + 1))
            net = sym.Activation(net, act_type="relu",
                                 name="relu%d_%d" % (i, j + 1))
        if i < 5:  # conv5 has no pool before RPN (stride 16 feature map)
            net = sym.Pooling(net, pool_type="max", kernel=(2, 2),
                              stride=(2, 2), name="pool%d" % i)
    return net


def _rpn(conv_feat, num_anchors):
    rpn_conv = sym.Convolution(conv_feat, kernel=(3, 3), pad=(1, 1),
                               num_filter=512, name="rpn_conv_3x3")
    rpn_relu = sym.Activation(rpn_conv, act_type="relu", name="rpn_relu")
    rpn_cls_score = sym.Convolution(rpn_relu, kernel=(1, 1), pad=(0, 0),
                                    num_filter=2 * num_anchors,
                                    name="rpn_cls_score")
    rpn_bbox_pred = sym.Convolution(rpn_relu, kernel=(1, 1), pad=(0, 0),
                                    num_filter=4 * num_anchors,
                                    name="rpn_bbox_pred")
    return rpn_cls_score, rpn_bbox_pred


def _proposal(rpn_cls_score, rpn_bbox_pred, im_info, num_anchors,
              feature_stride, scales, ratios, is_train):
    # softmax over {bg, fg} per anchor then Proposal decode + NMS
    rpn_cls_score_reshape = sym.Reshape(
        rpn_cls_score, shape=(0, 2, -1, 0), name="rpn_cls_score_reshape")
    rpn_cls_act = sym.SoftmaxActivation(
        rpn_cls_score_reshape, mode="channel", name="rpn_cls_act")
    rpn_cls_act_reshape = sym.Reshape(
        rpn_cls_act, shape=(0, 2 * num_anchors, -1, 0),
        name="rpn_cls_act_reshape")
    return sym.Proposal(
        rpn_cls_act_reshape, rpn_bbox_pred, im_info,
        feature_stride=feature_stride, scales=scales, ratios=ratios,
        rpn_pre_nms_top_n=12000 if is_train else 6000,
        rpn_post_nms_top_n=2000 if is_train else 300,
        threshold=0.7, rpn_min_size=16, name="rois")


def get_symbol_rpn(num_anchors=9, **kwargs):
    """RPN-only training graph (reference ``get_vgg_rpn``)."""
    data = sym.Variable("data")
    label = sym.Variable("label")
    bbox_target = sym.Variable("bbox_target")
    bbox_weight = sym.Variable("bbox_weight")
    conv_feat = _vgg_conv_body(data)
    rpn_cls_score, rpn_bbox_pred = _rpn(conv_feat, num_anchors)
    rpn_cls_score_reshape = sym.Reshape(
        rpn_cls_score, shape=(0, 2, -1), name="rpn_cls_score_reshape")
    cls_prob = sym.SoftmaxOutput(rpn_cls_score_reshape, label,
                                 multi_output=True, use_ignore=True,
                                 ignore_label=-1, name="cls_prob")
    bbox_loss_ = bbox_weight * sym.smooth_l1(rpn_bbox_pred - bbox_target,
                                             scalar=3.0,
                                             name="bbox_loss_smooth")
    bbox_loss = sym.MakeLoss(bbox_loss_, grad_scale=1.0 / 256,
                             name="bbox_loss")
    return sym.Group([cls_prob, bbox_loss])


def get_symbol_test(num_classes=21, num_anchors=9, feature_stride=16,
                    scales=(8, 16, 32), ratios=(0.5, 1, 2), **kwargs):
    """Detection inference graph (reference ``get_vgg_test``)."""
    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    conv_feat = _vgg_conv_body(data)
    rpn_cls_score, rpn_bbox_pred = _rpn(conv_feat, num_anchors)
    rois = _proposal(rpn_cls_score, rpn_bbox_pred, im_info, num_anchors,
                     feature_stride, scales, ratios, is_train=False)
    pool5 = sym.ROIPooling(conv_feat, rois, pooled_size=(7, 7),
                           spatial_scale=1.0 / feature_stride, name="roi_pool5")
    flat = sym.Flatten(pool5, name="flatten")
    fc6 = sym.FullyConnected(flat, num_hidden=4096, name="fc6")
    relu6 = sym.Activation(fc6, act_type="relu", name="relu6")
    fc7 = sym.FullyConnected(relu6, num_hidden=4096, name="fc7")
    relu7 = sym.Activation(fc7, act_type="relu", name="relu7")
    cls_score = sym.FullyConnected(relu7, num_hidden=num_classes,
                                   name="cls_score")
    cls_prob = sym.SoftmaxActivation(cls_score, name="cls_prob")
    bbox_pred = sym.FullyConnected(relu7, num_hidden=num_classes * 4,
                                   name="bbox_pred")
    return sym.Group([rois, cls_prob, bbox_pred])


def get_symbol_train(num_classes=21, num_anchors=9, feature_stride=16,
                     scales=(8, 16, 32), ratios=(0.5, 1, 2), **kwargs):
    """End-to-end training graph on precomputed ROI targets (reference
    ``get_vgg_train``): RPN losses + per-ROI head losses."""
    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    rpn_label = sym.Variable("label")
    rpn_bbox_target = sym.Variable("bbox_target")
    rpn_bbox_weight = sym.Variable("bbox_weight")
    roi_label = sym.Variable("roi_label")
    roi_bbox_target = sym.Variable("roi_bbox_target")
    roi_bbox_weight = sym.Variable("roi_bbox_weight")

    conv_feat = _vgg_conv_body(data)
    rpn_cls_score, rpn_bbox_pred = _rpn(conv_feat, num_anchors)

    # RPN losses
    rpn_cls_score_reshape = sym.Reshape(
        rpn_cls_score, shape=(0, 2, -1), name="rpn_cls_score_reshape")
    rpn_cls_prob = sym.SoftmaxOutput(
        rpn_cls_score_reshape, rpn_label, multi_output=True, use_ignore=True,
        ignore_label=-1, name="rpn_cls_prob")
    rpn_bbox_loss_ = rpn_bbox_weight * sym.smooth_l1(
        rpn_bbox_pred - rpn_bbox_target, scalar=3.0, name="rpn_loss_smooth")
    rpn_bbox_loss = sym.MakeLoss(rpn_bbox_loss_, grad_scale=1.0 / 256,
                                 name="rpn_bbox_loss")

    # region proposals (no gradient through the decode, like the reference)
    rois = _proposal(sym.BlockGrad(rpn_cls_score),
                     sym.BlockGrad(rpn_bbox_pred), im_info, num_anchors,
                     feature_stride, scales, ratios, is_train=True)

    # per-ROI head losses
    pool5 = sym.ROIPooling(conv_feat, rois, pooled_size=(7, 7),
                           spatial_scale=1.0 / feature_stride,
                           name="roi_pool5")
    flat = sym.Flatten(pool5, name="flatten")
    fc6 = sym.FullyConnected(flat, num_hidden=4096, name="fc6")
    relu6 = sym.Activation(fc6, act_type="relu", name="relu6")
    fc7 = sym.FullyConnected(relu6, num_hidden=4096, name="fc7")
    relu7 = sym.Activation(fc7, act_type="relu", name="relu7")
    cls_score = sym.FullyConnected(relu7, num_hidden=num_classes,
                                   name="cls_score")
    cls_prob = sym.SoftmaxOutput(cls_score, roi_label, name="cls_prob")
    bbox_pred = sym.FullyConnected(relu7, num_hidden=num_classes * 4,
                                   name="bbox_pred")
    bbox_loss_ = roi_bbox_weight * sym.smooth_l1(
        bbox_pred - roi_bbox_target, scalar=1.0, name="bbox_loss_smooth")
    bbox_loss = sym.MakeLoss(bbox_loss_, grad_scale=1.0 / 128,
                             name="bbox_loss")
    return sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss])
