"""Imperative torch-function bridge — the ``mx.th`` namespace.

Reference: ``python/mxnet/torch.py`` generates one python function per
registered (Lua)Torch tensor function so users can call torch math on
NDArrays (``mx.th.sigmoid(x)`` etc.).

TPU-native: PyTorch-CPU is the host math library; any ``torch.<fn>`` is
reachable by name, NDArray arguments round-trip through host memory.  This
is a *host* path (like the reference, where torch ran outside the MXNet
engine's device stream) — use graph ops for anything performance-critical.
"""

from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["TorchBridge", "th"]


class TorchBridge:
    """Attribute access resolves torch functions lazily:
    ``th.sigmoid(nd_array)`` -> ``torch.sigmoid`` on host, NDArray out."""

    def __getattr__(self, fn_name):
        try:
            import torch
        except ImportError as e:  # pragma: no cover - torch is baked in
            raise MXNetError("mx.th requires pytorch") from e
        fn = getattr(torch, fn_name, None)
        if fn is None or not callable(fn):
            raise AttributeError("torch has no function %r" % fn_name)

        def wrapper(*args, **kwargs):
            def conv(a):
                if isinstance(a, NDArray):
                    # copy: jax exports read-only buffers, torch wants writable
                    return torch.from_numpy(np.array(a.asnumpy()))
                if isinstance(a, (tuple, list)):
                    # tensor-sequence args (torch.cat/stack/...) — convert
                    # NDArray elements too
                    return type(a)(conv(x) for x in a)
                return a

            res = fn(*[conv(a) for a in args],
                     **{k: conv(v) for k, v in kwargs.items()})

            def back(r):
                if isinstance(r, torch.Tensor):
                    return array(np.ascontiguousarray(r.numpy()))
                return r

            if isinstance(res, (tuple, list)):
                return type(res)(back(r) for r in res)
            return back(res)

        wrapper.__name__ = fn_name
        return wrapper


th = TorchBridge()
