"""Pure-Python modules pluggable into the Module training loop.

Reference: ``python/mxnet/module/python_module.py`` (338 LoC) —
``PythonModule`` implements the BaseModule surface as mostly-empty
methods so users can write computation in numpy while participating in
``SequentialModule`` chains and the ``fit`` loop; ``PythonLossModule``
is the ready-made loss head (forward = identity on scores, backward =
user-supplied or numerical gradient via a callback).
"""

from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd
from ..initializer import Uniform
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Implements most module APIs as no-ops: a parameterless Python
    computation step (reference ``python_module.py:11``)."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = None if label_names is None \
            else list(label_names)
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- symbol information ----------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names or []

    @property
    def output_names(self):
        return self._output_names

    # -- input/output information ----------------------------------------
    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        """Empty list when the module takes no labels (reference
        ``python_module.py:62``)."""
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters (none by default) -------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        """Default: outputs are scores evaluated against the labels
        (reference ``python_module.py:120``)."""
        if self._label_shapes is None:
            return
        eval_metric.update(labels, self.get_outputs())

    # -- setup ------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        assert grad_req == "write", \
            "Python module only supports write gradient"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        names = [x[0] for x in data_shapes]
        assert names == self._data_names, \
            "data_shapes names %s != %s" % (names, self._data_names)
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        if label_shapes is not None:
            assert self._label_names is not None
            assert [x[0] for x in label_shapes] == self._label_names
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """Subclass hook: output shapes from data/label shapes."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """Loss head in Python: forward passes scores through, backward runs a
    user gradient function (reference ``python_module.py:219``)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, [name + "_output"],
                         logger=logger)
        self._name = name
        assert len(self._data_names) == 1
        assert self._label_names is None or len(self._label_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None:
            assert callable(grad_func)
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        """Outputs are the scores themselves: same shape as the input
        (reference ``python_module.py:256``)."""
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train and data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "PythonLossModule is a loss head"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        """Gradient of the loss wrt scores via the ``grad_func`` callback
        (reference ``python_module.py:285`` raises without one)."""
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(np.asarray(grad))
            self._scores_grad = grad
        else:
            raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores_grad]
