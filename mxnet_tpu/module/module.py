"""Module — the concrete single-symbol training module.

Reference: ``python/mxnet/module/module.py`` (708 LoC; bind :323,
init_optimizer :432, update :553) + ``executor_group.py``
(DataParallelExecutorGroup :77).

TPU-native data parallelism: where the reference builds one executor per GPU
and reduces gradients through KVStore (``executor_group.py`` decide_slices +
``comm.h`` Reduce), this Module binds ONE executor whose arrays are *global
jax.Arrays over a device mesh*: data/label sharded along the batch axis,
parameters replicated.  XLA GSPMD then compiles the gradient psum over ICI
into the step itself — the ``KVStore('device')`` allreduce with no server and
no separate comm phase.  A single context degenerates to a 1-device mesh.
"""

from __future__ import annotations

import logging
import pickle
import time as _time_mod

import numpy as np

from .. import compile_cache as _compile_cache
from .. import faults as _faults
from .. import metric as _metric
from .. import optimizer as opt
from .. import perfdebug as _perfdebug
from .. import random as _random
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..context import Context, cpu
from ..executor import Executor
from ..initializer import Uniform, InitDesc
from ..kvstore import KVStore
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from ..ndarray import NDArray, zeros as nd_zeros
from .base_module import BaseModule

__all__ = ["Module"]


def _parse_data_desc(data_shapes):
    out = []
    for d in data_shapes or []:
        if hasattr(d, "name"):
            out.append((d.name, tuple(d.shape)))
        else:
            out.append((d[0], tuple(d[1])))
    return out


class Module(BaseModule):
    """reference ``module.py:50``"""

    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, shard_rules=None):
        super().__init__(logger=logger)
        # context may be a jax.sharding.Mesh: Module.fit then runs the
        # whole dp(×tp×…) strategy through THIS surface — shard_rules
        # ([(param-name regex, PartitionSpec), ...]) places chosen
        # parameters over model axes and XLA inserts the implied
        # collectives (SURVEY §7.9 north star: `Module.fit` on a mesh)
        self._user_mesh = None
        from jax.sharding import Mesh as _JaxMesh

        if isinstance(context, _JaxMesh):
            self._user_mesh = context
            dev0 = context.devices.flat[0]
            context = [Context("cpu" if dev0.platform == "cpu" else "tpu",
                               0)]
        import re as _re

        self._shard_rules = [(_re.compile(p), spec)
                             for p, spec in (shard_rules or [])]
        if context is None:
            from ..context import current_context

            context = [current_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = list(context)
        self._work_load_list = work_load_list
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._mesh = None
        self._optimizer = None
        self._kvstore = None
        self._updater = None
        self._update_on_kvstore = False
        self._preload_opt_states = None
        self._grad_req = "write"
        self._fused_step = None
        self._pending_full = False  # staged single-dispatch train step
        self._dist_dp = False  # multi-process in-graph data parallelism
        self._dist_placed_states = set()

    # -- properties -------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._symbol.infer_shape(
            **{n: s for n, s in (self._data_shapes +
                                 (self._label_shapes or []))})[1]
        return list(zip(self._output_names, outs))

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n] for n in self._param_names}
        aux = dict(self._exec.aux_dict)
        return (arg, aux)

    # -- binding ----------------------------------------------------------
    def _make_mesh(self):
        import jax
        from jax.sharding import Mesh

        devices = [c.jax_device() for c in self._context]
        if len(set(devices)) != len(devices):
            raise MXNetError("duplicate devices in context list")
        return Mesh(np.array(devices), ("data",))

    def _batch_axis_name(self):
        """Mesh axis that shards the batch: 'data' when present, else the
        first axis."""
        names = self._mesh.axis_names
        return "data" if "data" in names else names[0]

    def _param_spec(self, name):
        from jax.sharding import PartitionSpec as P

        for prog, spec in self._shard_rules:
            if name is not None and prog.match(name):
                return spec
        return P()

    def _mesh_zero_names(self, names):
        """Parameters whose SGD update shards ZeRO-style over the mesh
        batch axis (docs/how_to/multi_devices.md "Sharded fit"): active
        only under ``kvstore='mesh'`` with a >1-device data axis, off
        via ``MXNET_MESH_ZERO=0``.  Eligibility (leading dim divisible
        by the world size, size floor) is
        :func:`~mxnet_tpu.kvstore_mesh.zero_eligible_names`."""
        import os

        kv = self._kvstore
        if self._mesh is None or kv is None \
                or not getattr(kv, "is_mesh", False):
            # clear, don't just skip: a re-init away from the mesh
            # kvstore must not leave _place_opt_state row-sharding
            # fresh states per a stale partition
            self._zero_names = frozenset()
            return ()
        if self._shard_rules:
            # ZeRO assumes dp-replicated params: a shard_rules module
            # keeps its TP layout and the plain fused step
            self._zero_names = frozenset()
            return ()
        env = (os.environ.get("MXNET_MESH_ZERO", "1"),
               os.environ.get("MXNET_MESH_ZERO_MIN_ELEMS"))
        # memoized: this runs on the per-batch dispatch path and the
        # answer only changes with the kvstore/mesh/param-set/env.  The
        # cache holds the kv/mesh objects (identity compare), so a
        # re-init onto a new plane recomputes
        cached = getattr(self, "_zero_names_cache", None)
        if cached is not None and cached[0] is kv \
                and cached[1] is self._mesh \
                and cached[2] == tuple(names) and cached[3] == env:
            return cached[4]
        if env[0] in ("0", "", "false"):
            zero = ()
        else:
            from ..kvstore_mesh import zero_eligible_names

            world = int(self._mesh.shape[self._batch_axis_name()])
            shapes = {n: tuple(self._exec.arg_dict[n].shape)
                      for n in names}
            zero = zero_eligible_names(names, shapes, world)
        # _place_opt_state consults this when it commits the optimizer
        # state arrays: ZeRO params' momentum rows shard with the update
        self._zero_names = frozenset(zero)
        self._zero_names_cache = (kv, self._mesh, tuple(names), env,
                                  zero)
        return zero

    def _snapshot_mesh_info(self):
        """Sharding descriptor for snapshot writes (None = unsharded):
        under ``kvstore='mesh'`` with world > 1 each snapshot generation
        is split into per-shard payload files stitched by a manifest
        entry (``checkpoint.write_snapshot``); ``MXNET_MESH_SHARDED_
        SNAPSHOT=0`` opts out."""
        import os

        kv = self._kvstore
        if self._mesh is None or kv is None \
                or not getattr(kv, "is_mesh", False):
            return None
        if os.environ.get("MXNET_MESH_SHARDED_SNAPSHOT", "1") \
                in ("0", "", "false"):
            return None
        axis = self._batch_axis_name()
        world = int(self._mesh.shape[axis])
        if world <= 1:
            return None
        return {"num_shards": world, "axis": axis,
                "mesh_axes": list(self._mesh.axis_names),
                "mesh_shape": [int(s) for s in self._mesh.devices.shape]}

    def _shard(self, arr, batch_axis, name=None):
        """Place an NDArray globally over the module mesh.

        Batch arrays shard over the batch axis; parameters follow their
        ``shard_rules`` spec (replicated by default — tensor parallelism
        is a rule away).  Multi-process (dist in-graph) mode additionally
        broadcasts non-batch arrays from rank 0 (the reference's Init
        broadcast, ``kvstore_dist.h:58-76``)."""
        if self._mesh is None:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._dist_dp:
            from .. import dist as _dist

            if batch_axis:
                return
            arr._jx = _dist.replicate(
                self._mesh,
                _dist.broadcast_from_root(np.asarray(arr._jx)))  # host-sync: ok — dist init-time broadcast
            return
        if len(self._context) == 1 and self._user_mesh is None:
            return
        spec = P(self._batch_axis_name()) if batch_axis \
            else self._param_spec(name)
        arr._jx = jax.device_put(arr._jx, NamedSharding(self._mesh, spec))

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """reference ``module.py:323``"""
        saved_params = None
        if force_rebind:
            if self._exec is not None and self.params_initialized:
                # the reference preserves parameter values across a
                # rebind; dropping them here would silently restart
                # training from whatever the fresh executor allocates
                saved_params = self.get_params()
            self._exec = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        assert not (not for_training and inputs_need_grad)
        self._data_shapes = _parse_data_desc(data_shapes)
        self._label_shapes = _parse_data_desc(label_shapes) \
            if label_shapes else []
        from .. import dist as _dist

        if self._user_mesh is not None:
            # explicit mesh: dp over the batch axis + whatever the
            # shard_rules place on the other axes
            self._mesh = self._user_mesh
            nbatch = self._mesh.shape[self._batch_axis_name()]
            for _, s in self._data_shapes + self._label_shapes:
                if s and s[0] % nbatch != 0:
                    raise MXNetError(
                        "batch size %d not divisible by mesh %r axis "
                        "size %d" % (s[0], self._batch_axis_name(), nbatch))
        elif _dist.is_initialized() and len(self._context) == 1:
            # TPU-native dist_sync: one jitted SPMD step over the GLOBAL
            # mesh; each process feeds its local batch shard and XLA
            # psums the gradients in-graph (SURVEY §5.8)
            import jax

            self._dist_dp = True
            self._mesh = _dist.global_mesh("data")
            local_devs = jax.local_device_count()
            for _, s in self._data_shapes + self._label_shapes:
                if s and s[0] % local_devs != 0:
                    raise MXNetError(
                        "local batch %d not divisible by %d local devices"
                        % (s[0], local_devs))
        elif len(self._context) > 1:
            self._mesh = self._make_mesh()
            if self._work_load_list and \
                    len(set(self._work_load_list)) > 1:
                # XLA sharding splits the batch uniformly; the
                # reference's weighted decide_slices has no SPMD analog
                self.logger.warning(
                    "work_load_list with non-uniform weights is ignored: "
                    "the mesh shards the batch evenly across devices")
            for _, s in self._data_shapes + self._label_shapes:
                if s and s[0] % len(self._context) != 0:
                    raise MXNetError(
                        "batch size %d not divisible by %d devices"
                        % (s[0], len(self._context)))
        shapes = dict(self._data_shapes + self._label_shapes)
        if self._dist_dp:
            # the executor binds GLOBAL batch shapes (local x processes)
            nproc = _dist.num_processes()
            shapes = {n: ((s[0] * nproc,) + tuple(s[1:])
                          if n in (self._data_names + self._label_names)
                          and s else s)
                      for n, s in shapes.items()}
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._param_names and n not in self._fixed_param_names \
                    and for_training:
                req[n] = grad_req if isinstance(grad_req, str) else \
                    grad_req.get(n, "write")
            elif n in self._data_names and inputs_need_grad:
                req[n] = "write"
            else:
                req[n] = "null"
        shared_exec = shared_module._exec if shared_module is not None else None
        self._exec = Executor._simple_bind(
            self._symbol, self._context[0], grad_req=req,
            shared_exec=shared_exec, **shapes)
        if self._dist_dp:
            self._exec._global_mesh = self._mesh
        elif self._mesh is not None:
            # single-process mesh: the executor needs the mesh to build
            # sharded program kinds (the ZeRO train_sgd_mesh step)
            self._exec._spmd_mesh = self._mesh
        # global placement over the mesh
        if self._mesh is not None:
            for n in self._symbol.list_arguments():
                batch_axis = n in self._data_names or n in self._label_names
                if self._exec.arg_dict.get(n) is not None:
                    self._shard(self._exec.arg_dict[n], batch_axis, n)
                if self._exec.grad_dict.get(n) is not None:
                    self._shard(self._exec.grad_dict[n], batch_axis, n)
            for n in self._aux_names:
                self._shard(self._exec.aux_dict[n], False, n)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            self.params_initialized = True
        if saved_params is not None:
            self.set_params(saved_params[0], saved_params[1],
                            force_init=True)

    def reshape(self, data_shapes, label_shapes=None):
        """reference module.py reshape"""
        assert self.binded
        self._data_shapes = _parse_data_desc(data_shapes)
        self._label_shapes = _parse_data_desc(label_shapes) \
            if label_shapes else []
        shapes = dict(self._data_shapes + self._label_shapes)
        self._exec = self._exec.reshape(allow_up_sizing=True, **shapes)

    # -- parameters -------------------------------------------------------
    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        """reference module.py:227"""
        assert self.binded, "call bind before initializing the parameters"
        if self.params_initialized and not force_init:
            return
        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache[name].copyto(arr)
            elif cache is not None and not allow_missing:
                raise RuntimeError("%s is not presented" % name)
            elif initializer is not None:
                initializer(InitDesc(name, attrs.get(name),
                                     global_init=initializer), arr)

        for name in self._param_names:
            _impl(name, self._exec.arg_dict[name], arg_params)
        for name in self._aux_names:
            _impl(name, self._exec.aux_dict[name], aux_params)
        # restore global sharding after host-side init writes
        if self._mesh is not None:
            for name in self._param_names:
                self._shard(self._exec.arg_dict[name], False, name)
            for name in self._aux_names:
                self._shard(self._exec.aux_dict[name], False, name)
        self.params_initialized = True

    # -- optimizer --------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """reference ``module.py:432``"""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), arg_params)
        if kvstore is not None and getattr(kvstore, "is_mesh", False) \
                and self._user_mesh is not kvstore.mesh \
                and (self._user_mesh is None
                     or getattr(self, "_kvstore_mesh_adopted", False)):
            # kvstore='mesh': the KVStore IS a device plane — adopt its
            # mesh and re-bind so every bound array becomes a global
            # jax Array (batch sharded over the data axis, params
            # replicated); GSPMD then compiles the gradient psum into
            # the step and push/pull never run per step.  A mesh the
            # USER passed as the module context is never clobbered
            # (their layout — axes, shard_rules targets, device subset
            # — wins; the mesh kvstore then just marks the in-graph
            # plane), but a previously kvstore-ADOPTED mesh is
            # re-adopted so re-initializing onto a new plane works
            self._user_mesh = kvstore.mesh
            self._kvstore_mesh_adopted = True
            self.bind(self._data_shapes, self._label_shapes or None,
                      for_training=self.for_training,
                      inputs_need_grad=self.inputs_need_grad,
                      force_rebind=True, grad_req=self._grad_req)
            arg_params = {n: self._exec.arg_dict[n]
                          for n in self._param_names}
        elif kvstore is not None \
                and getattr(kvstore, "in_graph_sync", False) \
                and not self._dist_dp:
            # the process group came up with the kvstore (after bind):
            # re-bind onto the global mesh, preserving parameters (bind
            # broadcasts rank-0 values during placement)
            self.bind(self._data_shapes, self._label_shapes or None,
                      for_training=self.for_training,
                      inputs_need_grad=self.inputs_need_grad,
                      force_rebind=True, grad_req=self._grad_req)
            arg_params = {n: self._exec.arg_dict[n]
                          for n in self._param_names}
        batch_size = self._data_shapes[0][1][0]
        if kvstore and "dist" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            # whether rescale_grad is framework-derived (1/global-batch)
            # or user-supplied: an elastic reshard recomputes the former
            # for the new world size but must never clobber the latter
            self._auto_rescale_grad = "rescale_grad" not in optimizer_params
            if self._auto_rescale_grad:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            self._auto_rescale_grad = False
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but "
                    "rescale_grad != 1.0/batch_size (%s vs. %s).",
                    optimizer.rescale_grad, rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        # a (re-)init starts a fresh updater/state generation: stale
        # placed-state bookkeeping would make _place_opt_state skip the
        # new states' mesh placement (single-device momentum entering a
        # mesh jit), and a stale zero partition would row-shard a
        # non-SGD optimizer's fresh states (whose update path never
        # recomputes it) per the old SGD partition
        self._dist_placed_states = set()
        self._zero_names_cache = None
        self._zero_names = frozenset()
        if kvstore:
            _initialize_kvstore(
                kvstore=kvstore,
                param_arrays=[[self._exec.arg_dict[n]]
                              for n in self._param_names],
                arg_params=arg_params, param_names=self._param_names,
                update_on_kvstore=update_on_kvstore)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """reference module.py borrow_optimizer"""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        # whether rescale_grad is framework-derived travels with the
        # optimizer: an elastic reshard recomputes it for the new world
        # only when the lender's init derived it (fit's init_optimizer
        # early-returns on the borrowed flag, so this is the only site
        # that can carry it over)
        self._auto_rescale_grad = getattr(
            shared_module, "_auto_rescale_grad", False)
        self.optimizer_initialized = True

    # -- compute ----------------------------------------------------------
    def _load_io(self, names, arrays):
        import jax

        for name, src in zip(names, arrays or []):
            if name not in self._exec.arg_dict:
                continue
            dst = self._exec.arg_dict[name]
            if self._dist_dp:
                # local batch shard -> global batch-sharded array
                from .. import dist as _dist

                loc = np.asarray(src._transfer_src()  # host-sync: ok — dist shards stage through host numpy
                                 if isinstance(src, NDArray)
                                 else src, dtype=dst.dtype)
                nproc = _dist.num_processes()
                if (loc.shape[0] * nproc,) + loc.shape[1:] != dst.shape:
                    raise MXNetError(
                        "input %r local shape %s does not tile to bound "
                        "global shape %s over %d processes"
                        % (name, loc.shape, dst.shape, nproc))
                dst._jx = _dist.shard_batch(self._mesh, loc)
                continue
            # _transfer_src: host-backed iterator batches hand over their
            # raw numpy buffer — device_put below is then the ONE copy
            jx = src._transfer_src() if isinstance(src, NDArray) else None
            if jx is None:
                dst[:] = src
                continue
            if jx.dtype != dst._jx.dtype:
                jx = jx.astype(dst._jx.dtype)
            if jx.shape != dst.shape:
                raise MXNetError("input %r shape %s != bound shape %s "
                                 "(reshape the module)" %
                                 (name, jx.shape, dst.shape))
            dst._jx = jax.device_put(jx, dst._jx.sharding)

    def forward(self, data_batch, is_train=None, _defer=False):
        """reference executor_group.py:355 forward + _load_data"""
        assert self.binded and self.params_initialized
        if not _defer:
            # a staged fused step must run before its batch data is
            # overwritten, or a later update() would apply stale grads
            self._materialize_pending()
        if is_train is None:
            is_train = self.for_training
        # zip with bind-time data_shapes order (= provide_data order), the
        # reference's _load_data positional contract (executor_group.py:369)
        self._load_io([n for n, _ in self._data_shapes], data_batch.data)
        if self._label_shapes and data_batch.label:
            self._load_io([n for n, _ in self._label_shapes],
                          data_batch.label)
        if not _defer:
            self._exec.forward(is_train=is_train)

    def backward(self, out_grads=None):
        """reference executor_group.py:481"""
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    # -- single-dispatch train step ---------------------------------------
    def _full_step_eligible(self):
        """fwd+bwd+update as ONE jit call: plain SGD, no kvstore, no
        monitor/profiler hooks, params-only grads all 'write'.

        Opt-in via ``MXNET_FUSE_TRAIN_STEP=1``: best-of-N A/B on the
        tunneled v5e backend (ResNet-50 b32, bench.py) measures the merged
        computation at ~1.8x the two-dispatch path — one tunnel round trip
        instead of two dominates at this step time.  The library default
        stays two-phase because the fused path restricts what get_outputs/
        get_input_grads can observe mid-step; bench.py and throughput-
        sensitive training loops should set the flag.  Numerics are
        identical either way (see
        tests/test_module.py::test_fused_full_step_matches_two_phase).
        """
        import os

        from .. import profiler as _profiler

        if os.environ.get("MXNET_FUSE_TRAIN_STEP", "0") != "1":
            return False
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            return False
        if type(self._optimizer) is not opt.SGD:
            return False
        if self._kvstore is not None and \
                not getattr(self._kvstore, "in_graph_sync", False):
            return False
        if self.inputs_need_grad or self._exec._monitor_callback is not None:
            return False
        if self._exec._segments is not None:
            return False  # group2ctx placement runs the segmented path
        if _profiler.running():
            return False  # unfused path keeps per-phase profiler spans
        diff = self._exec._diff_names()
        names = [n for n in self._param_names
                 if self._exec.grad_dict.get(n) is not None]
        return set(diff) == set(names) and \
            all(self._exec.grad_req[n] == "write" for n in diff)

    def forward_backward(self, data_batch):
        """Stages the batch for a fused fwd+bwd+update dispatch when
        eligible; ``update()`` then runs the whole step as one XLA
        computation.  Reading outputs/grads before ``update()`` falls back
        to the exact two-phase path."""
        if self._full_step_eligible():
            self.forward(data_batch, is_train=True, _defer=True)
            self._pending_full = True
            return
        self.forward(data_batch, is_train=True)
        self.backward()

    def _materialize_pending(self):
        """A staged batch is being observed before update(): run the
        normal fwd+bwd so outputs/grads exist, then clear the stage."""
        if self._pending_full:
            self._pending_full = False
            self._exec.forward(is_train=True)
            self._exec.backward()

    def _install_nan_guard(self, policy):
        """Arm (``policy`` set) or disarm (``None``) the in-graph NaN/Inf
        guard: the train kinds fold a logical-or reduction over
        outputs+grads into the step, the fused step additionally
        withholds a non-finite update in-graph, and the host reads one
        accumulated scalar at the ``MXNET_NAN_CHECK_PERIOD`` cadence
        (docs/resilience.md).  Disarming also drops any accumulated
        flag so it cannot leak into a later guarded fit."""
        if self._exec is not None:
            self._exec._nan_guard = policy is not None
            if policy is None:
                self._exec._nan_acc = None
                self._exec._nan_batch = None
                self._exec._nan_stale = False

    def _run_full_step(self):
        import jax
        import jax.numpy as jnp

        self._pending_full = False
        ex = self._exec
        optimizer = self._optimizer
        updater = self._updater
        names = [n for n in self._param_names
                 if ex.grad_dict.get(n) is not None]
        if not names:
            ex.forward(is_train=True)
            return
        # ZeRO eligibility must be known BEFORE the states are placed:
        # a sharded param's momentum commits row-sharded
        zero = self._mesh_zero_names(names)
        for idx in range(len(names)):
            if idx not in updater.states:
                updater.states[idx] = optimizer.create_state(
                    idx, ex.arg_dict[names[idx]])
            self._place_opt_state(idx, updater.states[idx], names[idx])
            optimizer._update_count(idx)
        lrs, wds = self._get_hyper_arrays(optimizer, len(names))
        clip = optimizer.clip_gradient \
            if optimizer.clip_gradient is not None else -1.0
        guard = bool(getattr(ex, "_nan_guard", False))
        if zero:
            # ZeRO fused step: reduce-scatter grads, sharded update,
            # all-gather params — no full-gradient materialization, so
            # grad_dict is left stale (like run_bulk's on-chip grads)
            fn = ex._get_fn(("train_sgd_mesh", tuple(names), tuple(zero),
                             optimizer.momentum, optimizer.rescale_grad,
                             clip, guard, self._batch_axis_name()))
        else:
            fn = ex._get_fn(("train_sgd", tuple(names), optimizer.momentum,
                             optimizer.rescale_grad, clip, guard))
        names_set = set(names)
        other = [n for n in ex.arg_names if n not in names_set]
        upd_vals = [ex.arg_dict[n]._jx for n in names]
        other_vals = [ex.arg_dict[n]._jx for n in other]
        aux = [a._jx for a in ex.aux_arrays]
        rng = ex.next_rng()
        moms = [updater.states[i]._jx for i in range(len(names))] \
            if optimizer.momentum != 0.0 else []
        grad_list = None
        if guard and zero:
            outs, new_aux, new_p, new_m, acc, batch_flag = fn(
                upd_vals, other_vals, aux, rng, moms, lrs, wds,
                ex._nan_acc_in())
            ex._nan_acc = acc
            ex._nan_batch = batch_flag
            ex._nan_stale = False
        elif guard:
            outs, new_aux, new_p, new_m, grad_list, acc, batch_flag = fn(
                upd_vals, other_vals, aux, rng, moms, lrs, wds,
                ex._nan_acc_in())
            ex._nan_acc = acc
            ex._nan_batch = batch_flag
            ex._nan_stale = False
        elif zero:
            outs, new_aux, new_p, new_m = fn(
                upd_vals, other_vals, aux, rng, moms, lrs, wds)
        else:
            outs, new_aux, new_p, new_m, grad_list = fn(
                upd_vals, other_vals, aux, rng, moms, lrs, wds)
        ex.outputs = [NDArray._from_jax(o, ex._ctx) for o in outs]
        for arr, v in zip(ex.aux_arrays, new_aux):
            arr._jx = v
        for n, p in zip(names, new_p):
            ex.arg_dict[n]._jx = p
        for i, m in enumerate(new_m):
            updater.states[i]._jx = m
        # keep grad_dict observable exactly like the two-phase path
        # (grad-norm logging etc. reads the current batch's gradients).
        # The ZeRO step never materializes full gradients (that is the
        # point — reduce-scatter, not all-reduce): grad_dict goes stale
        if grad_list is not None:
            for n, g in zip(names, grad_list):
                ex.grad_dict[n]._jx = g
        ex._pending_grads = None

    def run_bulk(self, batches, return_outputs=False):
        """Run ``len(batches)`` full fwd+bwd+update steps as ONE XLA
        dispatch: ``lax.scan`` over the stacked batches with params /
        momenta / aux (BN stats) as the scan carry.

        The reference cuts per-op dispatch cost by bulking engine ops
        into segments (``graph_executor.cc:678`` InitOpSegs,
        ``MXNET_EXEC_BULK_EXEC_TRAIN``); on TPU the per-*step* dispatch
        round trip is the analogous overhead, so this bulks whole steps.
        Requires the same eligibility as the fused step
        (``MXNET_FUSE_TRAIN_STEP=1``, plain SGD, local kvstore); falls
        back to per-batch ``forward_backward``+``update`` otherwise.
        With ``return_outputs=True`` every step's outputs are stacked
        and returned, and ``get_outputs()`` reflects the last step.
        With the default ``return_outputs=False`` the scan does NOT
        materialize the per-step output stack at all (at PTB shapes the
        stacked softmax is GBs of HBM nobody reads) — ``get_outputs()``
        is left stale, and per-step gradients are likewise not
        materialized (``grad_dict`` stale — the scan keeps them
        on-chip).

        ``return_outputs=True`` additionally returns, per symbol output,
        a host numpy array stacked over the batches (``(K, ...)``) — one
        transfer for all K steps' outputs, for metric updates.
        ``return_outputs="device"`` returns the same stacks WITHOUT the
        host transfer (jax arrays on the step device) — the sync-free
        fit path feeds them straight to device-resident metrics."""
        import jax
        import jax.numpy as jnp

        if not batches:
            return [] if return_outputs else None

        def _per_batch_fallback():
            per_batch = []
            for b in batches:
                self.forward_backward(b)
                self.update()
                if return_outputs:
                    outs = self.get_outputs()
                    per_batch.append(
                        [o._jx for o in outs] if return_outputs == "device"
                        else [o.asnumpy() for o in outs])  # host-sync: ok — explicit host-output mode
            if not return_outputs:
                return None
            stack = jnp.stack if return_outputs == "device" else np.stack
            return [stack([pb[i] for pb in per_batch])
                    for i in range(len(per_batch[0]))]

        if not self._full_step_eligible() or self._optimizer is None \
                or self._dist_dp:
            return _per_batch_fallback()
        ex = self._exec
        optimizer, updater = self._optimizer, self._updater
        names = [n for n in self._param_names
                 if ex.grad_dict.get(n) is not None]
        if not names:
            return _per_batch_fallback()
        if self._mesh_zero_names(names):
            # the ZeRO-sharded update lands per step (train_sgd_mesh);
            # the scan-bulked kind stays unsharded — fall back so the
            # sharded state layout is consistent across the whole fit
            return _per_batch_fallback()
        self._pending_full = False
        for idx in range(len(names)):
            if idx not in updater.states:
                updater.states[idx] = optimizer.create_state(
                    idx, ex.arg_dict[names[idx]])
        for _ in batches:
            for idx in range(len(names)):
                optimizer._update_count(idx)
        lrs, wds = self._get_hyper_arrays(optimizer, len(names))
        clip = optimizer.clip_gradient \
            if optimizer.clip_gradient is not None else -1.0
        scan_names = [n for n in (self._data_names + self._label_names)
                      if n in ex.arg_dict]
        fn = ex._get_fn(("train_sgd_scan", tuple(names), tuple(scan_names),
                         optimizer.momentum, optimizer.rescale_grad, clip,
                         bool(return_outputs)))
        dev = ex._ctx.jax_device()
        name_pos = {}
        for i, n in enumerate(self._data_names):
            name_pos[n] = ("data", i)
        for i, n in enumerate(self._label_names):
            name_pos[n] = ("label", i)

        def stack(n):
            kind, i = name_pos[n]
            dtype = ex.arg_dict[n]._jx.dtype
            vals = []
            for b in batches:
                v = (b.data if kind == "data" else b.label)[i]
                raw = v._transfer_src() if isinstance(v, NDArray) \
                    else jnp.asarray(v)
                vals.append(raw.astype(dtype))
            if all(isinstance(v, np.ndarray) for v in vals):
                # host-backed batches: stack on host, ship once
                return jax.device_put(np.stack(vals), dev)
            return jax.device_put(jnp.stack(vals), dev)

        # benchmark loops re-submit the same device-resident batches every
        # bulk; re-stacking them costs a dispatch round trip per input, so
        # memoize on the identity of the underlying buffers.  The cache
        # PINS those buffers (keyed list): an id() key alone would go
        # stale when fresh batches reuse a freed object's address
        keyed = [(b.data if k == "data" else b.label)[i]._jx
                 if isinstance((b.data if k == "data" else b.label)[i],
                               NDArray) else None
                 for k, i in name_pos.values() for b in batches]
        skey = tuple(id(v) if v is not None else None for v in keyed)
        cached = getattr(self, "_bulk_stack_cache", None)
        if cached is not None and cached[0] == skey and None not in skey:
            stacks = cached[1]
        else:
            with _telemetry.phase("stack", family="bulk"):
                stacks = [stack(n) for n in scan_names]
            self._bulk_stack_cache = (skey, stacks, keyed)
        names_set = set(names)
        static = [n for n in ex.arg_names
                  if n not in names_set and n not in scan_names]
        upd_vals = [ex.arg_dict[n]._jx for n in names]
        static_vals = [ex.arg_dict[n]._jx for n in static]
        aux = [a._jx for a in ex.aux_arrays]
        rng = ex.next_rng()
        moms = [updater.states[i]._jx for i in range(len(names))] \
            if optimizer.momentum != 0.0 else []
        call_args = (upd_vals, static_vals, aux, rng, moms, lrs, wds,
                     stacks)
        # abstract signature for bulk_cost_analysis (avals survive buffer
        # donation; holding the concrete arrays would not)
        self._last_bulk_sig = (fn, jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), call_args))
        # host-side dispatch wall time (XLA executes async; device time
        # shows up wherever the caller first blocks on results)
        with _telemetry.phase("dispatch", family="bulk"):
            outs_stack, new_aux, new_p, new_m = fn(*call_args)
        if outs_stack is not None:
            ex.outputs = [NDArray._from_jax(o[-1], ex._ctx)
                          for o in outs_stack]
        for arr, v in zip(ex.aux_arrays, new_aux):
            arr._jx = v
        for n, p in zip(names, new_p):
            ex.arg_dict[n]._jx = p
        for i, m in enumerate(new_m):
            updater.states[i]._jx = m
        ex._pending_grads = None
        if return_outputs == "device":
            return list(outs_stack)
        if return_outputs:
            return [np.asarray(o) for o in outs_stack]  # host-sync: ok — explicit host-output mode
        return None

    def bulk_cost_analysis(self):
        """XLA cost analysis of ONE compiled training step.

        Requires a prior :meth:`run_bulk` call (uses its signature).  The
        bulk step is a ``lax.scan`` over K batches; XLA's HLO cost
        analysis counts the loop body once, so the returned ``flops`` /
        ``bytes accessed`` are per-step figures — the measured FLOP count
        the benchmark divides by batch size for FLOPs/image (no
        hand-derived constants).  Returns the cost dict, or None when no
        bulk signature exists or analysis is unsupported on the backend.
        """
        sig = getattr(self, "_last_bulk_sig", None)
        if sig is None:
            return None
        fn, args = sig
        try:
            lowered = fn.lower(*args)
        except Exception:
            return None
        try:
            cost = lowered.compile().cost_analysis()
        except Exception:
            try:
                cost = lowered.cost_analysis()
            except Exception:
                return None
        # older jax returns a one-dict-per-device list
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        return cost

    def predict_bulk(self, batches):
        """Run ``len(batches)`` inference forwards as ONE XLA dispatch
        (lax.scan over the stacked inputs); returns a list of per-batch
        output lists.  The serving-throughput companion of ``run_bulk``."""
        import jax
        import jax.numpy as jnp

        assert self.binded and self.params_initialized
        if not batches:
            return []
        if self._dist_dp or self._exec._segments is not None:
            outs = []
            for b in batches:
                self.forward(b, is_train=False)
                outs.append(list(self.get_outputs()))
            return outs
        ex = self._exec
        scan_names = [n for n in (self._data_names + self._label_names)
                      if n in ex.arg_dict]
        fn = ex._get_fn(("predict_scan", tuple(scan_names)))
        dev = ex._ctx.jax_device()
        name_pos = {}
        for i, n in enumerate(self._data_names):
            name_pos[n] = ("data", i)
        for i, n in enumerate(self._label_names):
            name_pos[n] = ("label", i)

        def stack(n):
            kind, i = name_pos[n]
            vals = []
            for b in batches:
                arrs = b.data if kind == "data" else (b.label or [])
                if i >= len(arrs):  # label-less inference batches
                    vals.append(ex.arg_dict[n]._jx)
                    continue
                v = arrs[i]
                jx = v._jx if isinstance(v, NDArray) else jnp.asarray(v)
                vals.append(jx.astype(ex.arg_dict[n]._jx.dtype))
            return jax.device_put(jnp.stack(vals), dev)

        # cache pins the keyed buffers so id()s cannot be reused stale
        keyed = [v._jx if isinstance(v, NDArray) else None
                 for b in batches
                 for v in list(b.data) + list(b.label or [])]
        skey = tuple(id(v) if v is not None else None for v in keyed)
        cached = getattr(self, "_pred_stack_cache", None)
        if cached is not None and cached[0] == skey and None not in skey:
            stacks = cached[1]
        else:
            stacks = [stack(n) for n in scan_names]
            self._pred_stack_cache = (skey, stacks, keyed)
        static = [n for n in ex.arg_names if n not in scan_names]
        static_vals = [ex.arg_dict[n]._jx for n in static]
        aux = [a._jx for a in ex.aux_arrays]
        call_args = (static_vals, aux, ex.next_rng(), stacks)
        # same abstract signature record as run_bulk, so inference-only
        # benches get bulk_cost_analysis (measured FLOPs -> MFU) too
        self._last_bulk_sig = (fn, jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), call_args))
        outs_stack = fn(*call_args)
        result = []
        for k in range(len(batches)):
            result.append([NDArray._from_jax(o[k], ex._ctx)
                           for o in outs_stack])
        ex.outputs = result[-1]
        return result

    def update(self):
        """reference ``module.py:553`` + model.py:88/99.

        Fast path: for plain/momentum SGD with no kvstore, ONE jitted
        multi-tensor update over all parameters with donated buffers — the
        TPU analog of the reference's fused ``sgd_mom_update`` kernels
        without per-parameter dispatch.  Everything else goes through the
        kvstore/updater path for exact reference semantics.
        """
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        if self._pending_full:
            self._run_full_step()
            return
        local_kv = self._kvstore is None or (
            not self._update_on_kvstore and "dist" not in self._kvstore.type) \
            or getattr(self._kvstore, "in_graph_sync", False)
        if local_kv and self._updater is not None \
                and self._try_fused_update():
            return
        param_arrays = [[self._exec.arg_dict[n]] for n in self._param_names]
        grad_arrays = [[self._exec.grad_dict.get(n)]
                       for n in self._param_names]
        if self._mesh is not None and self._updater is not None:
            # mesh-placed weights/grads cannot enter an update jit with
            # locally-committed optimizer state: create + place states
            # (momentum, adam mean/var, ...) on the module mesh up front
            for index, n in enumerate(self._param_names):
                if self._exec.grad_dict.get(n) is None:
                    continue
                if index not in self._updater.states:
                    self._updater.states[index] = \
                        self._optimizer.create_state(
                            index, self._exec.arg_dict[n])
                self._place_opt_state(index, self._updater.states[index], n)
        if self._update_on_kvstore:
            _update_params_on_kvstore(param_arrays, grad_arrays,
                                      self._kvstore)
        else:
            # in_graph_sync: gradients were already globally psum'd inside
            # the step — pushing them through the PS would sum them across
            # num_workers a second time.  The PS stays a control plane
            # (init / explicit push-pull), not a gradient plane.
            kv = None if getattr(self._kvstore, "in_graph_sync", False) \
                else self._kvstore
            _update_params(param_arrays, grad_arrays, updater=self._updater,
                           num_device=1, kvstore=kv)

    def _get_hyper_arrays(self, optimizer, n):
        """Device copies of per-index lr/wd, re-uploaded only when a
        scheduler changes the values.  Multi-process mode passes host
        numpy (pjit replicates them) — a committed local array would
        clash with global-mesh arguments."""
        import jax.numpy as jnp

        lr_vals = tuple(optimizer._get_lr(i) for i in range(n))
        wd_vals = tuple(optimizer._get_wd(i) for i in range(n))
        cached = getattr(self, "_fused_hyper_cache", None)
        if cached is None or cached[0] != lr_vals or cached[1] != wd_vals:
            mk = np.asarray if self._dist_dp else \
                (lambda v, d=None: jnp.asarray(v, jnp.float32))
            self._fused_hyper_cache = (
                lr_vals, wd_vals,
                mk(np.asarray(lr_vals, np.float32)),   # host-sync: ok — python floats, no device buffer
                mk(np.asarray(wd_vals, np.float32)))  # host-sync: ok — python floats, no device buffer
            cached = self._fused_hyper_cache
        return cached[2], cached[3]

    def _place_opt_state(self, idx, state, name=None):
        """Optimizer state arrays (momentum etc.) join the module mesh —
        a locally-committed buffer cannot enter a jit whose other
        arguments are mesh-placed (multihost jit rejects it outright).
        States shard exactly like their parameter (a TP-sharded weight's
        momentum shards with it)."""
        if state is None or self._mesh is None \
                or idx in self._dist_placed_states:
            return state

        def place(arr):
            if arr is None:
                return
            if self._dist_dp:
                from .. import dist as _dist

                arr._jx = _dist.replicate(
                    self._mesh, np.asarray(arr._jx))  # host-sync: ok — dist init-time state placement
            else:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                spec = self._param_spec(name)
                if name is not None \
                        and name in getattr(self, "_zero_names", ()):
                    # ZeRO: the optimizer state stores row-sharded over
                    # the batch axis — each device holds 1/world of it
                    spec = P(self._batch_axis_name())
                arr._jx = jax.device_put(
                    arr._jx, NamedSharding(self._mesh, spec))

        # multi-array states (adam mean/var, rmsprop n/g/delta) place
        # every element alongside the parameter
        if isinstance(state, (tuple, list)):
            for s in state:
                place(s)
        else:
            place(state)
        self._dist_placed_states.add(idx)
        return state

    def _try_fused_update(self):
        import jax
        import jax.numpy as jnp

        optimizer = self._optimizer
        if type(optimizer) is not opt.SGD:
            return False
        names = [n for n in self._param_names
                 if self._exec.grad_dict.get(n) is not None]
        if not names:
            return True
        updater = self._updater
        zero = self._mesh_zero_names(names)
        # the mesh rides the key: a module re-initialized onto a new
        # device plane must not reuse a step whose shard_map/sharding
        # closures captured the old mesh
        step_key = (tuple(names), optimizer.momentum,
                    optimizer.rescale_grad, optimizer.clip_gradient,
                    tuple(zero), self._mesh)
        # states are created + mesh-placed EVERY call, not just when the
        # step compiles: _place_opt_state memoizes via
        # _dist_placed_states, and a mid-fit set_states restore (NaN
        # rollback, load_optimizer_states) re-commits host arrays AND
        # clears that memo — the re-placement must happen even when the
        # compiled step is cached.  Momentum lives in the Updater so
        # save/load_optimizer_states keeps working
        for idx, n in enumerate(names):
            if idx not in updater.states:
                updater.states[idx] = optimizer.create_state(
                    idx, self._exec.arg_dict[n])
            self._place_opt_state(idx, updater.states[idx], n)
        if self._fused_step is None \
                or getattr(self, "_fused_step_key", None) != step_key:
            momentum = optimizer.momentum
            rescale = optimizer.rescale_grad
            clip = optimizer.clip_gradient if optimizer.clip_gradient \
                is not None else -1.0

            from ..executor import sgd_step_math

            mstep = None
            if zero:
                # the shared per-param dispatch + layout pinning — the
                # same helper train_sgd_mesh compiles, so the two fused
                # paths cannot diverge numerically
                from ..kvstore_mesh import mesh_param_step

                mstep = mesh_param_step(
                    self._mesh, momentum, rescale, clip, zero,
                    axis_name=self._batch_axis_name())
            step_names = list(names)

            def step(params, grads, moms, lrs, wds):
                new_p, new_m = [], []
                for i, (p, g) in enumerate(zip(params, grads)):
                    m_in = moms[i] if momentum != 0.0 else None
                    if mstep is not None:
                        np_, nm, _flag = mstep(step_names[i], p, g,
                                               m_in, lrs[i], wds[i])
                    else:
                        np_, nm = sgd_step_math(
                            p, g, m_in, lrs[i], wds[i], momentum,
                            rescale, clip)
                    new_p.append(np_)
                    if nm is not None:
                        new_m.append(nm)
                return new_p, new_m

            self._fused_step = _compile_cache.instrument(
                _perfdebug.instrument(
                    jax.jit(step, donate_argnums=(0, 2)),
                    self._exec._symbol_name(), "fused_update"),
                self._exec._symbol_name(), "fused_update")
            self._fused_step_key = step_key
        # per-index bookkeeping keeps num_update/scheduler semantics
        for idx in range(len(names)):
            optimizer._update_count(idx)
        lrs, wds = self._get_hyper_arrays(optimizer, len(names))
        params = [self._exec.arg_dict[n]._jx for n in names]
        grads = [self._exec.grad_dict[n]._jx for n in names]
        moms = [updater.states[i]._jx for i in range(len(names))] \
            if optimizer.momentum != 0.0 else []
        new_p, new_m = self._fused_step(params, grads, moms, lrs, wds)
        for n, p in zip(names, new_p):
            self._exec.arg_dict[n]._jx = p
        for i, m in enumerate(new_m):
            updater.states[i]._jx = m
        return True

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        self._materialize_pending()
        if self._dist_dp:
            # per-worker view: this process's rows of the global batch
            # (the reference's per-worker outputs/metric semantics)
            from .. import dist as _dist
            from ..ndarray import array as nd_array

            return [nd_array(_dist.local_rows(o._jx))
                    for o in self._exec.outputs]
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        self._materialize_pending()
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        if isinstance(eval_metric, _metric.DeviceMetric) \
                and not self._dist_dp:
            if labels and self._label_shapes:
                # the labels were already loaded onto the executor's
                # device by forward()'s _load_io — hand the bound arrays
                # to the device metric instead of re-shipping (or worse,
                # re-materializing) the iterator's host buffers.  Only
                # when THIS batch carried labels: an unlabeled batch must
                # keep its (empty) list so the metric errors exactly like
                # the host path, not silently read stale bound buffers
                bound = [self._exec.arg_dict[n]
                         for n, _ in self._label_shapes
                         if n in self._exec.arg_dict]
                if len(bound) == len(labels):
                    labels = bound
            # under the in-graph NaN guard, a flagged batch's statistics
            # are zeroed inside the metric's accumulation jit — exact
            # skip-batch metric semantics at ANY check cadence, no sync
            skip = self._exec._nan_batch \
                if getattr(self._exec, "_nan_guard", False) else None
            eval_metric.update(labels, self.get_outputs(), skip=skip)
            return
        eval_metric.update(labels, self.get_outputs())

    def _device_put_batch(self, name, arr):
        """Prefetch-thread H2D placer (``fit(prefetch_to_device=True)``):
        move ONE input batch array onto the bound array's device — with
        the MODULE's sharding for that input, so mesh contexts get the
        same batch-axis placement ``Module._shard`` committed — while
        the previous step's compute is still in flight.  Runs on the
        ``DevicePrefetchIter`` background thread; ``_load_io``'s
        device_put then finds the data already resident (a no-op put).

        The sharding is recomputed from the mesh, NOT read off the
        bound buffer: on a fresh bind the buffer can still carry its
        single-device placement (allocation happens before ``_shard``
        commits the mesh layout, and a rebind can race the background
        producer), and a single-device put would force the step to
        re-lay out every batch on the blocking path — the exact copy
        the prefetch thread exists to hide.  Regression-pinned by
        tests/test_mesh_kvstore.py."""
        import jax

        dst = self._exec.arg_dict.get(name) if self._exec is not None \
            else None
        if dst is None:
            return arr
        sharding = dst._jx.sharding
        if self._mesh is not None and not self._dist_dp:
            from jax.sharding import NamedSharding, PartitionSpec as P

            batch_axis = name in self._data_names \
                or name in self._label_names
            spec = P(self._batch_axis_name()) if batch_axis \
                else self._param_spec(name)
            sharding = NamedSharding(self._mesh, spec)
        raw = arr._transfer_src() if isinstance(arr, NDArray) \
            else np.asarray(arr)  # host-sync: ok — host iterator batch, not a device buffer
        if isinstance(raw, np.ndarray) and raw.dtype != dst._jx.dtype:
            raw = raw.astype(dst._jx.dtype)
        return NDArray._from_jax(jax.device_put(raw, sharding), dst._ctx)

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    # -- cross-replica integrity audit (docs/resilience.md) ---------------
    def _audit_names(self):
        """The replicated state the integrity audit fingerprints: every
        parameter whose spec is fully replicated (TP-sharded
        ``shard_rules`` params live intentionally split — there is no
        cross-replica copy to compare) plus the aux states (BN stats).
        ZeRO params ARE included: the update's all-gather re-enters
        them replicated, which is how the ZeRO-owned rows get their
        post-gather check."""
        from jax.sharding import PartitionSpec as P

        rep = P()
        names = [n for n in self._param_names
                 if self._param_spec(n) == rep
                 and self._exec.arg_dict.get(n) is not None]
        return names + list(self._aux_names)

    def _audit_array(self, name):
        d = self._exec.arg_dict.get(name)
        return d if d is not None else self._exec.aux_dict[name]

    def _bitflip_replica(self, name):
        """fault 'audit.bitflip': rebuild ``name``'s replicated array
        with ONE bit flipped on device 0's replica only — the observable
        state of a host/HBM bit-flip or a corrupt collective that the
        next audit must catch.  Uses per-device buffers under the same
        replicated sharding, so nothing but the audited bit pattern
        changes."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        arr = self._audit_array(name)
        host = np.ascontiguousarray(np.asarray(arr._jx))  # host-sync: ok — fault-injection path, not the hot loop
        bad = host.copy()
        bad.view(np.uint8).flat[0] ^= 1
        devs = list(self._mesh.devices.flat)
        bufs = [jax.device_put(bad if i == 0 else host, d)
                for i, d in enumerate(devs)]
        arr._jx = jax.make_array_from_single_device_arrays(
            host.shape, NamedSharding(self._mesh, P()), bufs)
        self.logger.warning(
            "fault 'audit.bitflip': flipped one bit of %r on replica 0",
            name)

    def _run_integrity_audit(self, policy, prefix, epoch, nbatch):
        """One cross-replica integrity audit
        (:func:`~mxnet_tpu.kvstore_mesh.build_replica_audit`): fold
        per-param bit-pattern checksums per mesh replica, compare
        in-graph, read ONE tiny result pair.  A mismatch is silent
        divergence/corruption — replicated state must agree exactly —
        and trips ``policy``: ``'raise'`` →
        :class:`~mxnet_tpu.sentinel.ReplicaDivergence`, ``'rollback'``
        → restore the last good checkpoint.  No-op (debug-logged once)
        off the mesh plane or on a 1-device mesh, where there are no
        replicas to disagree."""
        kv = self._kvstore
        if self._mesh is None or self._dist_dp or kv is None \
                or not getattr(kv, "is_mesh", False) \
                or int(self._mesh.shape[self._batch_axis_name()]) <= 1:
            if not getattr(self, "_audit_skip_logged", False):
                self._audit_skip_logged = True
                self.logger.debug(
                    "integrity audit skipped: needs fit(kvstore='mesh') "
                    "with a >1-device data axis")
            return None
        names = self._audit_names()
        if not names:
            return None
        if _faults.should_fire("audit.bitflip"):
            self._bitflip_replica(names[0])
        arrays = [self._audit_array(n)._jx for n in names]
        key = (self._mesh,
               tuple((a.shape, str(a.dtype)) for a in arrays))
        cached = getattr(self, "_audit_fn_cache", None)
        if cached is None or cached[0] != key:
            from ..kvstore_mesh import build_replica_audit

            cached = (key, _perfdebug.instrument(
                build_replica_audit(self._mesh, self._batch_axis_name()),
                self._exec._symbol_name(), "replica_audit"))
            self._audit_fn_cache = cached
        res = np.asarray(cached[1](arrays))  # host-sync: ok — the audit's one tiny result read
        count, first = int(res[0]), int(res[1])
        _telemetry.inc("reliability.audits")
        if count == 0:
            return 0
        bad = names[first] if 0 <= first < len(names) else "?"
        world = int(self._mesh.shape[self._batch_axis_name()])
        _telemetry.inc("reliability.divergences")
        _telemetry.event("reliability.divergence", epoch=epoch,
                         batch=nbatch, arrays=count, first=bad,
                         action=policy)
        _perfdebug.flight_dump("divergence", epoch=epoch, nbatch=nbatch,
                               arrays=count, first=bad)
        if policy == "rollback":
            self.logger.warning(
                "integrity audit: %d replicated array(s) diverged "
                "bit-wise across the %d-way mesh (first: %r); rolling "
                "back to the last valid checkpoint", count, world, bad)
            self._rollback_to_checkpoint(prefix)
            return count
        from ..sentinel import ReplicaDivergence

        raise ReplicaDivergence(
            "cross-replica integrity audit failed at epoch %d batch %d: "
            "%d replicated array(s) diverged bit-wise across the %d-way "
            "mesh (first: %r) — silent divergence or corruption "
            "(replicated state must agree exactly; set "
            "MXNET_AUDIT_POLICY=rollback to auto-recover)"
            % (epoch, nbatch, count, world, bad))

    # -- compile-once warm-up (docs/how_to/perf.md "Compile once") --------
    def warm_from_manifest(self, manifest):
        """Replay a compile-once warm-up manifest: AOT-build + compile
        every executable a previous run of this model recorded, BEFORE
        the first real batch dispatches.  With the persistent compile
        cache populated (``MXNET_COMPILE_CACHE_DIR``) the whole replay
        is disk loads — a ``resume="auto"`` restart performs zero cold
        XLA compiles on the training hot path.  State-safe: nothing
        executes, so parameters / optimizer state / rng are untouched
        (exact-resume bit-identity is preserved).  Returns the replay
        summary dict."""
        assert self.binded, "call bind before warm_from_manifest"
        entries = manifest.get("entries", []) \
            if isinstance(manifest, dict) else list(manifest)
        # the registry records per process, so a multi-model run's
        # manifest can carry foreign executables: prefer the entries
        # recorded for THIS executor when any match (a replay of a
        # foreign program would just burn a trace and log an error)
        mine = [e for e in entries
                if e.get("exec") == self._exec._symbol_name()]
        if mine:
            entries = mine
        t0 = _time_mod.perf_counter()
        summary = self._exec.precompile(entries, logger=self.logger)
        dt = _time_mod.perf_counter() - t0
        _telemetry.inc("compile_cache.manifest.replays")
        _telemetry.event("compile_cache.manifest_replay",
                         exec=self._exec._symbol_name(),
                         seconds=round(dt, 3), **summary)
        self.logger.info(
            "compile_cache: warm-up manifest replayed in %.2fs — %d "
            "program(s) pre-built, %d skipped, %d error(s), %d "
            "fingerprint change(s)", dt, summary["replayed"],
            summary["skipped"], summary["errors"],
            summary["fingerprint_changes"])
        return summary

    # -- checkpointing ----------------------------------------------------
    def _capture_state_arrays(self):
        """Device-side capture for async snapshots (docs/resilience.md):
        one dispatched device-to-device ``NDArray.copy()`` per parameter
        / aux / optimizer-state array — NO host sync on the training
        loop; the background writer does the device→host transfer when
        it serializes.  Returns ``(arg, aux, opt_states, opt_counts)``
        where ``opt_states`` mirrors ``Updater.states`` (None when the
        optimizer plane lives on the kvstore) and ``opt_counts`` carries
        the scheduler-relevant update counters."""
        import jax

        assert self.binded and self.params_initialized
        # a staged fused step must land before its params are captured
        self._materialize_pending()
        ex = self._exec
        # ONE jitted multi-array copy instead of a dispatch per array:
        # at snapshot cadence the per-dispatch round trip would be the
        # whole capture cost
        flat = []

        def _grab(arr):
            flat.append(arr._jx)
            return len(flat) - 1

        param_idx = {n: _grab(ex.arg_dict[n]) for n in self._param_names}
        aux_idx = {n: _grab(a) for n, a in ex.aux_dict.items()}
        state_spec = None
        has_states = self.optimizer_initialized \
            and self._updater is not None and not self._update_on_kvstore
        if has_states:
            def _spec(s):
                if s is None:
                    return None
                if isinstance(s, (tuple, list)):
                    return ("seq", type(s), [_spec(x) for x in s])
                if isinstance(s, NDArray):
                    return ("nd", _grab(s), s._ctx)
                return ("raw", s)

            state_spec = {i: _spec(s)
                          for i, s in self._updater.states.items()}
        fn = getattr(self, "_capture_copy_fn", None)
        if fn is None:
            fn = jax.jit(lambda xs: [x + 0 for x in xs])
            self._capture_copy_fn = fn
        copies = fn(flat) if flat else []

        def _wrap(i, ctx):
            return NDArray._from_jax(copies[i], ctx)

        arg = {n: _wrap(i, ex.arg_dict[n]._ctx)
               for n, i in param_idx.items()}
        aux = {n: _wrap(i, ex.aux_dict[n]._ctx)
               for n, i in aux_idx.items()}
        opt_states = None
        opt_counts = None
        if has_states:
            def _build(spec):
                if spec is None:
                    return None
                kind = spec[0]
                if kind == "seq":
                    return spec[1](_build(x) for x in spec[2])
                if kind == "nd":
                    return _wrap(spec[1], spec[2])
                return spec[1]

            opt_states = {i: _build(s) for i, s in state_spec.items()}
        if self._optimizer is not None:
            opt_counts = {
                "num_update": int(self._optimizer.num_update),
                "index_update_count": {
                    str(k): int(v) for k, v in
                    self._optimizer._index_update_count.items()}}
        return arg, aux, opt_states, opt_counts

    def _elastic_param_entries(self):
        """The kvstore key space of this module's parameters:
        ``[(key, name)]`` in the exact ``init_optimizer`` enumeration
        order — the domain of the elastic reshard's
        :func:`~mxnet_tpu.elastic.assign_keys` key-ownership map."""
        return list(enumerate(self._param_names))

    def _elastic_pull_params(self):
        """Pull every parameter from the (just-rehydrated) coordinator
        into the bound executor — the final step of the elastic reshard
        cycle, after which every member holds the identical
        post-reshard state."""
        assert self._kvstore is not None
        for i, n in enumerate(self._param_names):
            self._kvstore.pull(i, [self._exec.arg_dict[n]], priority=-i)

    def _restore_opt_snapshot(self, states_bytes, opt_counts):
        """Resume half of :meth:`_capture_state_arrays`: re-install the
        pickled updater states and the optimizer's update counters so a
        resumed run's lr schedule continues exactly."""
        if states_bytes is not None and self._updater is not None:
            from ..elastic import SERVER_STATES_KEY

            payload = None
            if SERVER_STATES_KEY.encode() in states_bytes:
                # the marker string can only appear in the pickle of an
                # elastic leader snapshot's marker dict — the bytes scan
                # gates the unpickle so a plain (non-elastic) updater
                # tree is never deserialized twice; the dict check below
                # stays authoritative
                try:
                    payload = pickle.loads(states_bytes)
                except Exception:  # noqa: broad-except — not a plain
                    # pickle; let set_states apply its own format handling
                    payload = None
            if isinstance(payload, dict) and SERVER_STATES_KEY in payload:
                # an elastic leader snapshot: its .states carry the
                # SERVER-side updater blobs (re-installed on the
                # coordinator by the reshard cycle), not a local updater
                # tree — installing them locally would corrupt the state
                # structure.  A non-elastic resume of an elastic prefix
                # restarts local momentum instead.
                self.logger.warning(
                    "resume: snapshot optimizer states are elastic "
                    "coordinator-side blobs; local updater momentum "
                    "restarts from zero")
            else:
                self._updater.set_states(states_bytes)
                # unpickled states are locally-committed host arrays —
                # the next update jit re-places them on the module mesh
                self._dist_placed_states.clear()
        if opt_counts and self._optimizer is not None:
            self._optimizer.num_update = int(
                opt_counts.get("num_update", self._optimizer.num_update))
            idx = opt_counts.get("index_update_count") or {}
            self._optimizer._index_update_count = {
                int(k): int(v) for k, v in idx.items()}

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """reference module.py save_checkpoint"""
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """reference module.py load"""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params_cache = args
        mod._aux_params_cache = auxs

        orig_bind = mod.bind

        def bind_and_set(*a, **kw):
            orig_bind(*a, **kw)
            mod.set_params(args, auxs, allow_missing=False)

        mod.bind = bind_and_set
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            from ..base import atomic_write_bytes

            atomic_write_bytes(fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as fin:
                self._updater.set_states(fin.read())
            # the unpickled states are locally-committed host arrays —
            # they must be re-placed on the module mesh before the next
            # update jit sees them
            self._dist_placed_states.clear()
