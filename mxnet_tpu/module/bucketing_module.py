"""BucketingModule — variable-length sequence training.

Reference: ``python/mxnet/module/bucketing_module.py`` (SURVEY §2.4 item 4,
§3.1 BucketingModule variant): ``sym_gen(bucket_key) -> (symbol, data_names,
label_names)``; on first sight of a bucket, bind a new Module sharing
parameters with the default bucket's module.

TPU design note: each bucket is one jit specialization (compile cache keyed
by shape); parameter sharing is by NDArray identity via ``shared_module``
(the analog of the shared ``data_pool_`` rebind,
``graph_executor.cc:336-340``), so all buckets train one set of weights and
XLA caches one executable per bucket shape.
"""

from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """reference ``bucketing_module.py:18``"""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    def _call_sym_gen(self, key):
        res = self._sym_gen(key)
        if isinstance(res, tuple):
            return res
        return (res, ("data",), ("softmax_label",))

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._call_sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._call_sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def get_params(self):
        assert self.binded and self.params_initialized
        return self._curr_module.get_params()

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """reference ``bucketing_module.py`` bind — binds the DEFAULT bucket."""
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """reference ``bucketing_module.py:300-325``"""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key])
            module.params_initialized = True
            if self.optimizer_initialized:
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key,
                           data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for mod in self._buckets.values():
            mod.install_monitor(mon)
