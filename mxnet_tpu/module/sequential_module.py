"""SequentialModule — a pipeline of modules executed back-to-back.

API parity with the reference's ``python/mxnet/module/
sequential_module.py`` (``add(module, take_labels=..., auto_wiring=...)``,
the ``META_*`` constants, the BaseModule contract), re-built around an
explicit stage record instead of the reference's parallel
``_modules``/``_metas`` lists and ``dir()``-reflection over ``META_``
attributes: each ``add`` appends a ``_Stage`` carrying the module and
its wiring flags, and every pass (bind / forward / backward / metric)
iterates stages.

Stage semantics:

- ``take_labels``: this stage's ``bind``/``update_metric`` see the real
  label shapes/batch labels (loss heads); all other stages bind
  label-free.
- ``auto_wiring``: the previous stage's outputs are renamed
  positionally onto this stage's ``data_names`` before binding (lets a
  generic head consume whatever the backbone produced).
"""

from __future__ import annotations

import logging
from typing import NamedTuple

from ..initializer import Uniform
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class _Stage(NamedTuple):
    module: object
    meta: dict  # all meta kwargs as given (incl. subclass extras)

    # read through to the dict so legacy mutation via seq._metas[i][...]
    # (a reference-supported pattern) stays effective at bind time
    @property
    def take_labels(self):
        return bool(self.meta.get(SequentialModule.META_TAKE_LABELS, False))

    @property
    def auto_wiring(self):
        return bool(self.meta.get(SequentialModule.META_AUTO_WIRING, False))


class SequentialModule(BaseModule):
    """Chain of modules; data flows stage i -> stage i+1, gradients flow
    back stage i+1 -> stage i (reference ``sequential_module.py:15``)."""

    # public constants kept for reference-API compatibility:
    # seq.add(m, **{SequentialModule.META_TAKE_LABELS: True})
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._stages = []
        self._label_shapes = None

    # kept as a property so reference-style introspection of ._modules
    # (and this file's own older callers) keeps working
    @property
    def _modules(self):
        return [s.module for s in self._stages]

    @property
    def _metas(self):
        return [s.meta for s in self._stages]

    def add(self, module, **kwargs):
        # reference pattern: subclasses may declare extra META_* class
        # constants; any such value is an accepted meta key
        known = {getattr(type(self), a) for a in dir(type(self))
                 if a.startswith("META_")}
        unknown = set(kwargs) - known
        if unknown:
            raise ValueError("Unknown meta %s (known: %s)"
                             % (sorted(unknown), sorted(known)))
        self._stages.append(_Stage(module=module, meta=dict(kwargs)))
        # any topology change invalidates bind/init state
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # ---- shapes & names -------------------------------------------------

    @property
    def data_names(self):
        return self._stages[0].module.data_names if self._stages else []

    @property
    def output_names(self):
        return self._stages[-1].module.output_names if self._stages else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._stages[0].module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._stages[-1].module.output_shapes

    # ---- parameters -----------------------------------------------------

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for stage in self._stages:
            arg, aux = stage.module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for stage in self._stages:
            stage.module.init_params(
                initializer=initializer, arg_params=arg_params,
                aux_params=aux_params, allow_missing=allow_missing,
                force_init=force_init)
        self._assert_unique_param_names()
        self.params_initialized = True

    def _assert_unique_param_names(self):
        """A param name appearing in two stages would silently shadow in
        get_params(); fail loudly with both stage positions instead."""
        owner = {}
        for i, stage in enumerate(self._stages):
            arg, aux = stage.module.get_params()
            for name in list(arg) + list(aux):
                if name in owner:
                    raise ValueError(
                        "Duplicated parameter name %r: stage %d (%s) and "
                        "stage %d (%s)" % (
                            name, owner[name],
                            type(self._stages[owner[name]].module).__name__,
                            i, type(stage.module).__name__))
                owner[name] = i

    # ---- bind / optimizer ----------------------------------------------

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        if inputs_need_grad:
            assert for_training
        assert shared_module is None, "Shared module is not supported"
        assert self._stages, "Attempting to bind an empty Sequential"
        self.binded = True

        flowing_shapes = data_shapes
        for i, stage in enumerate(self._stages):
            if stage.auto_wiring:
                names = stage.module.data_names
                assert len(names) == len(flowing_shapes)
                flowing_shapes = [
                    (name, shape)
                    for name, (_, shape) in zip(names, flowing_shapes)]
            stage.module.bind(
                data_shapes=flowing_shapes,
                label_shapes=label_shapes if stage.take_labels else None,
                for_training=for_training,
                # interior stages always need input grads to keep the
                # chain's backward flowing; stage 0 only if asked
                inputs_need_grad=bool(
                    for_training and (inputs_need_grad or i > 0)),
                force_rebind=force_rebind, shared_module=None,
                grad_req=grad_req)
            flowing_shapes = stage.module.output_shapes

        self._label_shapes = label_shapes \
            if any(s.take_labels for s in self._stages) else None

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        for stage in self._stages:
            stage.module.init_optimizer(
                kvstore=kvstore, optimizer=optimizer,
                optimizer_params=optimizer_params, force_init=force_init)
        self.optimizer_initialized = True

    # ---- compute --------------------------------------------------------

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from ..io import DataBatch

        batch = data_batch
        for i, stage in enumerate(self._stages):
            stage.module.forward(batch, is_train=is_train)
            if i + 1 == len(self._stages):
                break
            outputs = stage.module.get_outputs()
            names = [x.name if hasattr(x, "name") else x[0]
                     for x in stage.module.output_shapes]
            assert len(names) == len(outputs)
            # fresh batch per stage: outputs become the next stage's
            # data, labels ride through untouched for take_labels heads
            batch = DataBatch(
                data=outputs, label=batch.label, pad=batch.pad,
                index=batch.index,
                provide_data=[(n, x.shape) for n, x in zip(names, outputs)],
                provide_label=batch.provide_label)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i in range(len(self._stages) - 1, -1, -1):
            self._stages[i].module.backward(out_grads=out_grads)
            if i > 0:
                out_grads = self._stages[i].module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        for stage in self._stages:
            stage.module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._stages[-1].module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._stages[0].module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        for stage in self._stages:
            if stage.take_labels:
                stage.module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for stage in self._stages:
            stage.module.install_monitor(mon)
